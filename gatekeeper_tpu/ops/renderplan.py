"""Compiled violation rendering: the deny path without the interpreter.

The TPU/numpy mask tells the driver WHICH (constraint, resource) cells are
violation candidates; producing the violation *messages* for those cells
used to re-run the whole generator-based interpreter per cell — a 10-13x
latency penalty exactly on the requests that matter most (BENCH_r05:
ingest_violating_unique_p50 25.9ms vs ingest_unique_p50 2.5ms).

This module compiles each template's ``violation[{"msg": ...}]`` head into
a **message plan** at vectorize time and *binds* it per constraint, so a
flagged cell renders by direct field reads + the real sprintf builtin —
no QueryContext, no per-cell freeze(params), no backtracking search.

Plan classes (exported as render_cells_total{plan=...}):

- ``static``: every clause's violation object is a bind-time constant
  (message text depends only on constraint parameters — e.g. the
  port-range family).  Rendering a cell is a per-clause condition check
  plus a precomputed object.
- ``slots``: the violation object reads review/slot/keyset fields (the
  dominant Gatekeeper shape: ``sprintf`` over literals + field refs).
  Rendering gathers the referenced values from a per-row view and calls
  the same builtins the interpreter would.
- ``interp``: anything the plan compiler does not recognize — or any
  template whose vectorized program is not exact — falls back to the
  interpreter, cell by cell.  The residual tail is drained by a bounded
  worker pool (RenderPool) instead of a serial loop.

Exactness contract: a bound plan is only produced when the template's
VProgram compiled **exactly** (no dropped statements), and the bound
condition evaluator runs the same IR over *direct* (unpacked) review
values with full Rego semantics — ``compare`` for cross-type ordering,
undefined-propagation for missing fields, real builtin calls for string
predicates and formatting, and RSet dedup + canonical sort for the final
violation list.  The rendered output is therefore byte-identical to
``TemplatePolicy.eval_violations`` by construction (asserted corpus-wide
by tests/test_render_parity.py), and the plan render *replaces* the
interpreter both as renderer and as the device-mask exactness filter.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..engine import builtins as bi
from ..engine.value import (
    FrozenDict,
    RSet,
    UNDEFINED,
    compare,
    freeze,
    thaw,
    values_equal,
)
from ..rego.ast import (
    ArrayTerm,
    Call,
    Node,
    ObjectTerm,
    Ref,
    Scalar,
    Var,
)
from .vexpr import (
    AnyParam,
    BoolOp,
    ColRef,
    Cmp,
    Const,
    Lit,
    ParamElemRef,
    ParamRef,
    ReduceSlots,
    SetCountCmp,
    StrPred,
    Truthy,
    VProgram,
)

# plan tiers (metric label values)
STATIC, SLOTS, INTERP = "static", "slots", "interp"

# pure, deterministic builtins a message/details expression may call.
# Anything outside this set (wall clock, uuid, data access, http) makes
# the clause dynamic -> interpreter.
_PURE_CALLS = {
    ("sprintf",), ("concat",), ("format_int",), ("lower",), ("upper",),
    ("replace",), ("trim",), ("trim_left",), ("trim_right",),
    ("trim_prefix",), ("trim_suffix",), ("substring",), ("to_number",),
    ("count",), ("sort",), ("split",), ("json", "marshal"),
    ("array", "concat"),
}


class _Dynamic(Exception):
    """Raised during plan compilation when a term is unrecognized."""


# ---------------------------------------------------------------------------
# value plans: the violation-object expression tree
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class VConst:
    value: Any  # frozen


@dataclass(frozen=True)
class VReviewRef:
    segs: Tuple[str, ...]  # review-rooted ([]-free)


@dataclass(frozen=True)
class VSlotRef:
    rel: Tuple[str, ...]  # entity-relative ([]-free); () = the entity


@dataclass(frozen=True)
class VParamRef:
    segs: Tuple[str, ...]  # resolved to a constant at bind time


@dataclass(frozen=True)
class VKeySet:
    iter_paths: Tuple[Tuple[str, ...], ...]
    rel: Tuple[str, ...]
    exclude: Tuple[str, ...]


@dataclass(frozen=True)
class VParamIds:
    ppath: Tuple[str, ...]
    subpath: Tuple[str, ...] = ()


@dataclass(frozen=True)
class VSetDiff:
    left: Any  # VKeySet | VParamIds
    right: Any


@dataclass(frozen=True)
class VObj:
    pairs: Tuple[Tuple[str, Any], ...]


@dataclass(frozen=True)
class VArr:
    items: Tuple[Any, ...]


@dataclass(frozen=True)
class VCall:
    path: Tuple[str, ...]
    args: Tuple[Any, ...]


@dataclass(frozen=True)
class VBinOp:
    op: str
    lhs: Any
    rhs: Any


@dataclass(frozen=True)
class VFmt:
    """Bind-time-split sprintf: len(segments) == len(args) + 1 literal
    segments interleaved with %v/%s-formatted args.  The char-by-char
    sprintf parse runs once at bind, not per rendered cell."""

    segments: Tuple[str, ...]
    args: Tuple[Any, ...]


def _split_simple_fmt(fmt: str) -> Optional[List[str]]:
    """Split a sprintf format whose verbs are all plain %v/%s (no flags,
    width, or precision) into literal segments; None when any other verb
    or spec appears (the generic builtin then runs per cell)."""
    segs: List[str] = []
    cur: List[str] = []
    i, n = 0, len(fmt)
    while i < n:
        ch = fmt[i]
        if ch != "%":
            cur.append(ch)
            i += 1
            continue
        if i + 1 < n and fmt[i + 1] == "%":
            cur.append("%")
            i += 2
            continue
        if i + 1 < n and fmt[i + 1] in "vs":
            segs.append("".join(cur))
            cur = []
            i += 2
            continue
        return None
    segs.append("".join(cur))
    return segs


@dataclass(frozen=True)
class ClausePlan:
    """Compiled violation-object plan for one violation rule clause."""

    obj: Any  # value plan for the rule key (the violation object)
    # definedness guards: value plans for every recognized non-iteration
    # assignment rhs in the clause body.  The interpreter fails the body
    # when such an assignment's rhs is undefined (missing field, failed
    # benign call) even if the assigned var is never used; the MASK may
    # drop that (widening is sound there), but the plan render is the
    # exactness filter and must reproduce it per binding.
    guards: Tuple[Any, ...] = ()


# ---------------------------------------------------------------------------
# plan compilation (vectorize time; driven by ops/vectorizer.py)
# ---------------------------------------------------------------------------


def _always_defined_sym(vec, term, env) -> bool:
    """True when the term provably never evaluates undefined: literals
    and comprehension-derived sets/arrays (empty when their source is
    absent, never undefined)."""
    from .vectorizer import (
        SConst, SKeySet, SParamIds, SPredAny, SSetDiff, _Unsupported,
    )

    try:
        sym = vec._resolve(term, env, {"slot": None}, allow_compr=True)
    except _Unsupported:
        return False
    return isinstance(sym, (SConst, SKeySet, SParamIds, SSetDiff, SPredAny))


def compile_clause_plan(vec, rule, env: dict, ast_env: dict,
                        slot_iter, guards=(), helper_guards=()) -> Optional[ClausePlan]:
    """Compile the clause's rule key (the violation object) into a value
    plan, or None when any part is unrecognized (the clause then renders
    through the interpreter).  ``vec`` is the live Vectorizer (for its
    symbolic resolver); ``env``/``ast_env`` are the clause's symbolic and
    AST assignment environments; ``slot_iter`` the clause's iteration
    axis (or None); ``guards`` the clause body's assignment rhs terms
    whose definedness must hold, and ``helper_guards`` the
    disjunct-scoped ones from inlined helpers (accepted only when
    always-defined — a failing helper body falsifies just its disjunct,
    which a clause-level guard cannot express)."""
    key = rule.key
    if key is None:
        return None
    if helper_guards:
        # the vectorizer already filtered always-defined ones (in the
        # helper's own env); anything left cannot be expressed as a
        # clause-level guard
        return None
    try:
        guard_plans = []
        for g in guards:
            if _always_defined_sym(vec, g, env):
                continue
            guard_plans.append(
                _compile_value(vec, g, env, ast_env, slot_iter, depth=0)
            )
        obj = _compile_value(vec, key, env, ast_env, slot_iter, depth=0)
    except _Dynamic:
        return None
    except Exception:
        return None
    if not isinstance(obj, (VObj,)):
        # the webhook/audit contract consumes dict-shaped violations
        return None
    if not any(k == "msg" for k, _ in obj.pairs):
        return None
    # guards that already appear as subtrees of the violation object are
    # redundant (the object evaluation fails on the same undefined input
    # with identical no-violation semantics) — and the common case,
    # `msg := sprintf(...)`, would otherwise format every message twice
    obj_subplans = set()
    _collect_subplans(obj, obj_subplans)
    guard_plans = [g for g in guard_plans if g not in obj_subplans]
    return ClausePlan(obj=obj, guards=tuple(guard_plans))


def _collect_subplans(plan, out: set):
    out.add(plan)
    if isinstance(plan, VObj):
        for _k, v in plan.pairs:
            _collect_subplans(v, out)
    elif isinstance(plan, (VArr, VCall, VFmt)):
        for v in (plan.items if isinstance(plan, VArr) else plan.args):
            _collect_subplans(v, out)
    elif isinstance(plan, VBinOp):
        _collect_subplans(plan.lhs, out)
        _collect_subplans(plan.rhs, out)
    elif isinstance(plan, VSetDiff):
        _collect_subplans(plan.left, out)
        _collect_subplans(plan.right, out)


def _compile_value(vec, t: Node, env, ast_env, slot_iter, depth: int):
    if depth > 16:
        raise _Dynamic()
    if isinstance(t, Scalar):
        return VConst(freeze(t.value))
    if isinstance(t, ObjectTerm):
        pairs = []
        for k, v in t.pairs:
            if not (isinstance(k, Scalar) and isinstance(k.value, str)):
                raise _Dynamic()
            pairs.append((
                k.value,
                _compile_value(vec, v, env, ast_env, slot_iter, depth + 1),
            ))
        return VObj(tuple(pairs))
    if isinstance(t, ArrayTerm):
        return VArr(tuple(
            _compile_value(vec, x, env, ast_env, slot_iter, depth + 1)
            for x in t.items
        ))
    if isinstance(t, Call):
        path = tuple(t.path)
        if path not in _PURE_CALLS or bi.lookup(path) is None:
            raise _Dynamic()
        return VCall(path, tuple(
            _compile_value(vec, a, env, ast_env, slot_iter, depth + 1)
            for a in t.args
        ))
    from ..rego.ast import BinOp as _BinOp

    if isinstance(t, _BinOp):
        return VBinOp(
            t.op,
            _compile_value(vec, t.lhs, env, ast_env, slot_iter, depth + 1),
            _compile_value(vec, t.rhs, env, ast_env, slot_iter, depth + 1),
        )
    if isinstance(t, Var):
        sym = _resolve_sym(vec, t, env)
        if sym is not None:
            return _sym_to_plan(sym, slot_iter)
        rhs = ast_env.get(t.name)
        if rhs is not None:
            return _compile_value(vec, rhs, env, ast_env, slot_iter,
                                  depth + 1)
        raise _Dynamic()
    if isinstance(t, Ref):
        sym = _resolve_sym(vec, t, env)
        if sym is None:
            raise _Dynamic()
        return _sym_to_plan(sym, slot_iter)
    raise _Dynamic()


def _resolve_sym(vec, t: Node, env):
    """Symbolic resolution via the Vectorizer, None on failure (no
    side-effecting column registration happens on these paths)."""
    from .vectorizer import SConst, SKeySet, SParamIds, SPath, SSetDiff
    from .vectorizer import _Unsupported

    try:
        sym = vec._resolve(t, env, {"slot": None}, allow_compr=True)
    except _Unsupported:
        return None
    if isinstance(sym, (SConst, SPath, SKeySet, SParamIds, SSetDiff)):
        return sym
    return None


def _sym_to_plan(sym, slot_iter):
    from .vectorizer import SConst, SKeySet, SParamIds, SPath, SSetDiff

    if isinstance(sym, SConst):
        return VConst(freeze(sym.value))
    if isinstance(sym, SPath):
        if sym.root == "review":
            return VReviewRef(tuple(sym.segs))
        if sym.root == "params":
            return VParamRef(tuple(sym.segs))
        if isinstance(sym.root, tuple) and sym.root[0] == "slot":
            if slot_iter is None or sym.root[1] != slot_iter:
                raise _Dynamic()  # ref to a foreign iteration axis
            return VSlotRef(tuple(sym.segs))
        raise _Dynamic()
    if isinstance(sym, SKeySet):
        return VKeySet(tuple(sym.iter_paths), tuple(sym.rel),
                       tuple(sym.exclude))
    if isinstance(sym, SParamIds):
        return VParamIds(tuple(sym.ppath), tuple(sym.subpath))
    if isinstance(sym, SSetDiff):
        return VSetDiff(_sym_to_plan(sym.left, slot_iter),
                        _sym_to_plan(sym.right, slot_iter))
    raise _Dynamic()


# ---------------------------------------------------------------------------
# row views: direct (exact) field access over one review/resource
# ---------------------------------------------------------------------------


def strip_request_meta(frozen_review):
    """Identical content minus per-request metadata (uid): the content
    memo key (see driver._strip_request_meta, whose semantics this
    mirrors — memo_safe policies provably never read the stripped
    fields)."""
    if isinstance(frozen_review, FrozenDict) and "uid" in frozen_review:
        return FrozenDict(
            {k: frozen_review[k] for k in frozen_review._d if k != "uid"}
        )
    return frozen_review


class _Absent:
    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debug aid
        return "<absent>"


ABSENT = _Absent()

# RowView cache-miss sentinel (None and ABSENT are both valid cached
# values: null fields cache None, missing fields cache ABSENT)
_MISS = object()


def _walk_path(obj, path: Tuple[str, ...], i: int, out: list):
    """Same traversal as ops/columns.py: [] flattens arrays (and ONLY
    arrays), string segments index dicts."""
    if i == len(path):
        out.append(obj)
        return
    seg = path[i]
    if seg == "[]":
        if isinstance(obj, list):
            for item in obj:
                _walk_path(item, path, i + 1, out)
        return
    if isinstance(obj, dict) and seg in obj:
        _walk_path(obj[seg], path, i + 1, out)


def _get_rel(obj, segs: Tuple[str, ...]):
    cur = obj
    for seg in segs:
        if isinstance(cur, dict) and seg in cur:
            cur = cur[seg]
        else:
            return ABSENT
    return cur


class RowView:
    """Cached direct-value access for one review dict: slot entities per
    iteration group, scalar paths, keysets, and the (lazily computed)
    frozen form for interpreter fallback / memo keys.  Shared across every
    constraint rendered for the row, so each distinct path is walked once
    per row regardless of the installed-constraint count."""

    __slots__ = ("review", "_frozen", "_memo_frozen", "_entities",
                 "_scalars", "_keysets", "_frozen_vals")

    def __init__(self, review: dict, frozen_review=None):
        self.review = review
        self._frozen = frozen_review
        self._memo_frozen = None
        self._entities: Dict[Tuple, list] = {}
        self._scalars: Dict[Tuple, Any] = {}
        self._keysets: Dict[Tuple, Any] = {}
        self._frozen_vals: Dict[Tuple, Any] = {}

    def frozen(self):
        if self._frozen is None:
            self._frozen = freeze(self.review)
        return self._frozen

    def memo_frozen(self):
        """The uid-stripped frozen review — the content memo key — built
        (and hashed) ONCE per row.  Building it per cell re-hashed the
        whole review content per constraint, which dominated the bulk
        render pass at 500 installed constraints."""
        if self._memo_frozen is None:
            self._memo_frozen = strip_request_meta(self.frozen())
        return self._memo_frozen

    def entities(self, iter_paths: Tuple[Tuple[str, ...], ...]) -> list:
        got = self._entities.get(iter_paths)
        if got is None:
            got = []
            for p in iter_paths:
                _walk_path(self.review, p, 0, got)
            self._entities[iter_paths] = got
        return got

    def scalar(self, segs: Tuple[str, ...]):
        # _MISS sentinel, not None: a JSON-null field caches as None and
        # must not re-walk per cell
        got = self._scalars.get(segs, _MISS)
        if got is _MISS:
            got = _get_rel(self.review, segs)
            self._scalars[segs] = got
        return got

    def scalar_frozen(self, segs: Tuple[str, ...]):
        got = self._frozen_vals.get(segs, _MISS)
        if got is _MISS:
            raw = self.scalar(segs)
            got = UNDEFINED if raw is ABSENT else freeze(raw)
            self._frozen_vals[segs] = got
        return got

    def keyset(self, iter_paths, rel, exclude) -> frozenset:
        """The comprehension ``{k | PATH[k]; k != excl...}`` evaluated
        exactly: dict targets contribute keys whose value is not false;
        list targets contribute indices of not-false elements (OPA walks
        arrays by index); excluded literals are dropped."""
        ck = (iter_paths, rel, exclude)
        got = self._keysets.get(ck)
        if got is None:
            keys = set()
            for ent in self.entities(iter_paths):
                target = _get_rel(ent, rel) if rel else ent
                if isinstance(target, dict):
                    for k, v in target.items():
                        if v is not False and k not in exclude:
                            keys.add(freeze(k))
                elif isinstance(target, list):
                    for i, v in enumerate(target):
                        if v is not False and i not in exclude:
                            keys.add(i)
            got = frozenset(keys)
            self._keysets[ck] = got
        return got


# ---------------------------------------------------------------------------
# binding (per constraint) and application (per cell)
# ---------------------------------------------------------------------------


def _param_get(params, segs: Tuple[str, ...]):
    cur = params
    for seg in segs:
        if isinstance(cur, FrozenDict) and seg in cur:
            cur = cur[seg]
        else:
            return UNDEFINED
    return cur


def _param_elems(value) -> list:
    """Wildcard iteration over a frozen parameter value, mirroring the
    interpreter's _walk: arrays yield items, objects yield values (sorted
    key order), sets yield items, scalars yield nothing."""
    if isinstance(value, tuple):
        return list(value)
    if isinstance(value, FrozenDict):
        return [value[k] for k in value.sorted_keys()]
    if isinstance(value, RSet):
        return list(value.sorted_items())
    return []


@dataclass
class BoundClause:
    never: bool = False
    res_conds: Tuple = ()  # resource-level bound cond closures
    slot_conds: Tuple = ()  # slot-level bound cond closures
    # definedness-guard value closures (ClausePlan.guards), split by axis:
    # an UNDEFINED guard value fails the clause (resource level) or the
    # binding (slot level), like the interpreter's assignment failure
    res_guards: Tuple = ()
    slot_guards: Tuple = ()
    slot_iter: Optional[Tuple] = None
    obj_fn: Any = None  # compiled value closure (violation object)
    obj_static: Any = None  # precomputed frozen object when constant


@dataclass
class BoundPlan:
    """A template plan bound to one constraint's parameters."""

    tier: str  # STATIC | SLOTS
    clauses: List[BoundClause] = field(default_factory=list)
    # True when the packed match kernel is provably exact for this
    # constraint (no label/namespace selectors — the only fields the
    # packed match can over-approximate through, ops/pack.py): mask-driven
    # callers may then skip the native constraint_matches re-check
    match_exact: bool = False

    def apply(self, row: RowView) -> list:
        """Exact violations for (this constraint, row.review): evaluates
        each clause's conditions over direct values, materializes the
        violation object per firing binding, and returns the deduped,
        canonically-sorted, thawed list — the eval_violations contract."""
        items = set()
        for cl in self.clauses:
            if cl.never:
                continue
            ok = True
            for c in cl.res_conds:
                if not c(row, None):
                    ok = False
                    break
            if ok:
                for g in cl.res_guards:
                    if g(row, None) is UNDEFINED:
                        ok = False
                        break
            if not ok:
                continue
            if cl.slot_iter is None:
                obj = (cl.obj_static if cl.obj_static is not None
                       else cl.obj_fn(row, None))
                if obj is not UNDEFINED:
                    items.add(obj)
                continue
            for ent in row.entities(cl.slot_iter):
                fired = True
                for c in cl.slot_conds:
                    if not c(row, ent):
                        fired = False
                        break
                if fired:
                    for g in cl.slot_guards:
                        if g(row, ent) is UNDEFINED:
                            fired = False
                            break
                if not fired:
                    continue
                obj = (cl.obj_static if cl.obj_static is not None
                       else cl.obj_fn(row, ent))
                if obj is not UNDEFINED:
                    items.add(obj)
        if not items:
            return []
        return [thaw(v) for v in RSet(items)]


# ---- bound conditions: compiled to closures --------------------------------
# Binding emits plain Python closures fn(row, entity) -> value/bool rather
# than a node tree: the per-cell isinstance dispatch of a tree walk
# measured as the dominant render cost once sprintf was pre-split.  Each
# closure returns a body-statement truth value — False covers both
# Rego-false and undefined (statement failure).


def _const_getter(v):
    def get(row, entity, _v=v):
        return _v

    return get


def _operand_getter(op, params):
    """fn(row, entity) -> frozen value or UNDEFINED for a Lit/ParamRef/
    ColRef operand (ParamElemRef binds inside the AnyParam unroll)."""
    if isinstance(op, Lit):
        return _const_getter(freeze(op.value))
    if isinstance(op, ParamRef):
        return _const_getter(_param_get(params, tuple(op.ppath)))
    if isinstance(op, ColRef):
        kind, _ip, rel, _ex = op.colkey
        rel = tuple(rel)
        if kind == "scalar":
            def get_scalar(row, entity, _segs=rel):
                return row.scalar_frozen(_segs)

            return get_scalar
        if kind == "slot":
            if rel:
                def get_slot(row, entity, _segs=rel):
                    v = _get_rel(entity, _segs)
                    return UNDEFINED if v is ABSENT else freeze(v)

                return get_slot

            def get_entity(row, entity):
                return freeze(entity)

            return get_entity
    raise _Dynamic()


def _cond_false(row, entity):
    return False


def _cond_true(row, entity):
    return True


def _compile_truthy(get, negate):
    if negate:
        def f(row, entity):
            v = get(row, entity)
            return v is UNDEFINED or v is False

        return f

    def t(row, entity):
        v = get(row, entity)
        return v is not UNDEFINED and v is not False

    return t


_CMP_RANKS = {"<": (-1,), "<=": (-1, 0), ">": (1,), ">=": (0, 1)}


def _compile_cmp(op, a, b):
    if op == "==":
        def eq(row, entity):
            va = a(row, entity)
            if va is UNDEFINED:
                return False
            vb = b(row, entity)
            if vb is UNDEFINED:
                return False
            return values_equal(va, vb)

        return eq
    if op == "!=":
        def ne(row, entity):
            va = a(row, entity)
            if va is UNDEFINED:
                return False
            vb = b(row, entity)
            if vb is UNDEFINED:
                return False
            return not values_equal(va, vb)

        return ne
    ranks = _CMP_RANKS[op]

    def rel(row, entity):
        va = a(row, entity)
        if va is UNDEFINED:
            return False
        vb = b(row, entity)
        if vb is UNDEFINED:
            return False
        return compare(va, vb) in ranks

    return rel


def _compile_strpred(pred, get, pat, negate):
    if not isinstance(pat, str):
        # builtin error for every cell -> statement always fails
        base = _cond_false
    elif pred == "startswith":
        def base(row, entity):
            v = get(row, entity)
            return isinstance(v, str) and v.startswith(pat)
    elif pred == "endswith":
        def base(row, entity):
            v = get(row, entity)
            return isinstance(v, str) and v.endswith(pat)
    elif pred == "contains":
        def base(row, entity):
            v = get(row, entity)
            return isinstance(v, str) and pat in v
    elif pred == "re_match":
        fn = bi.lookup(("re_match",))

        def base(row, entity):
            v = get(row, entity)
            if not isinstance(v, str):
                return False
            try:
                return bool(fn(pat, v))
            except bi.BuiltinError:
                return False
    else:
        raise _Dynamic()
    if not negate:
        return base

    def neg(row, entity):
        return not base(row, entity)

    return neg


def _compile_all(conds):
    if not conds:
        return _cond_true
    if len(conds) == 1:
        return conds[0]

    def f(row, entity, _cs=tuple(conds)):
        for c in _cs:
            if not c(row, entity):
                return False
        return True

    return f


def _bind_cond(node, params, prog: VProgram):
    """One VExpr condition -> closure fn(row, entity) -> bool with exact
    interpreter semantics over direct values."""
    if isinstance(node, Const):
        return _cond_true if node.value else _cond_false
    if isinstance(node, Truthy):
        return _compile_truthy(
            _operand_getter(node.operand, params), node.negate
        )
    if isinstance(node, Cmp):
        return _compile_cmp(
            node.op,
            _operand_getter(node.lhs, params),
            _operand_getter(node.rhs, params),
        )
    if isinstance(node, StrPred):
        return _compile_strpred(
            node.pred, _operand_getter(node.operand, params),
            _strpred_pattern(node, params), node.negate,
        )
    if isinstance(node, AnyParam):
        value = _param_get(params, tuple(node.ppath))
        branches = tuple(
            _compile_all(tuple(
                _bind_elem_cond(c, elem, params, prog) for c in node.inner
            ))
            for elem in _param_elems(value)
        )
        if not branches:
            return _cond_false

        def any_branch(row, entity, _bs=branches):
            for b in _bs:
                if b(row, entity):
                    return True
            return False

        return any_branch
    if isinstance(node, SetCountCmp):
        lget = _set_getter(node.left, params)
        rget = _set_getter(node.right, params)
        import operator

        cmpf = {
            ">": operator.gt, ">=": operator.ge, "<": operator.lt,
            "<=": operator.le, "==": operator.eq, "!=": operator.ne,
        }[node.op]
        n = node.n

        def setcount(row, entity):
            return cmpf(len(lget(row) - rget(row)), n)

        return setcount
    if isinstance(node, BoolOp):
        children = tuple(
            _bind_cond(c, params, prog) for c in node.children
        )
        if node.op == "not":
            c0 = children[0]

            def negated(row, entity):
                return not c0(row, entity)

            return negated
        if node.op == "and":
            return _compile_all(children)

        def any_child(row, entity, _cs=children):
            for c in _cs:
                if c(row, entity):
                    return True
            return False

        return any_child
    if isinstance(node, ReduceSlots):
        inner = _compile_all(tuple(
            _bind_cond(c, params, prog) for c in node.inner
        ))
        ip = tuple(node.iter_key)

        def reduce_slots(row, entity, _inner=inner, _ip=ip):
            for ent in row.entities(_ip):
                if _inner(row, ent):
                    return True
            return False

        return reduce_slots
    raise _Dynamic()


def _bind_elem_cond(node, elem, params, prog):
    """Bind an AnyParam inner condition for ONE parameter element:
    ParamElemRef operands become constants of that element."""
    def op_of(op):
        if isinstance(op, ParamElemRef):
            v = elem
            for seg in op.subpath:
                if isinstance(v, FrozenDict) and seg in v:
                    v = v[seg]
                else:
                    return _const_getter(UNDEFINED)
            return _const_getter(v)
        return _operand_getter(op, params)

    if isinstance(node, Cmp):
        return _compile_cmp(node.op, op_of(node.lhs), op_of(node.rhs))
    if isinstance(node, StrPred):
        if isinstance(node.rhs, ParamElemRef):
            pat = op_of(node.rhs)(None, None)
        else:
            pat = _strpred_pattern(node, params)
        return _compile_strpred(
            node.pred, op_of(node.operand), pat, node.negate
        )
    if isinstance(node, Truthy):
        return _compile_truthy(op_of(node.operand), node.negate)
    raise _Dynamic()


def _strpred_pattern(node: StrPred, params):
    if isinstance(node.rhs, Lit):
        return freeze(node.rhs.value)
    if isinstance(node.rhs, ParamRef):
        return _param_get(params, tuple(node.rhs.ppath))
    raise _Dynamic()


def _param_id_set(ppath, subpath, params) -> frozenset:
    vals = set()
    for elem in _param_elems(_param_get(params, tuple(ppath))):
        v = elem
        ok = True
        for seg in subpath:
            if isinstance(v, FrozenDict) and seg in v:
                v = v[seg]
            else:
                ok = False
                break
        if ok:
            vals.add(v)
    return frozenset(vals)


def _set_getter(side, params):
    """fn(row) -> frozenset for a SetCountCmp side."""
    kind, key = side
    if kind == "keyset":
        _k, iter_paths, rel, exclude = key
        ip, rl, ex = tuple(iter_paths), tuple(rel), tuple(exclude)

        def get_keys(row, _ip=ip, _rl=rl, _ex=ex):
            return row.keyset(_ip, _rl, _ex)

        return get_keys
    ppath, subpath = key
    return lambda row, _v=_param_id_set(ppath, subpath, params): _v


# ---- bound value plans -----------------------------------------------------


def _bind_value(plan, params):
    """Partial-evaluate a value plan against the constraint parameters:
    VParamRef/VParamIds collapse to constants; a fully-constant subtree
    collapses to VConst.  Raises _Dynamic only at compile; binding never
    does — an undefined parameter becomes VConst(UNDEFINED), which makes
    the owning clause render nothing (the interpreter's msg-assignment
    failure semantics)."""
    if isinstance(plan, VConst):
        return plan
    if isinstance(plan, VParamRef):
        return VConst(_param_get(params, plan.segs))
    if isinstance(plan, VParamIds):
        return VConst(RSet(_param_id_set(plan.ppath, plan.subpath, params)))
    if isinstance(plan, VObj):
        pairs = tuple((k, _bind_value(v, params)) for k, v in plan.pairs)
        if all(isinstance(v, VConst) for _k, v in pairs):
            if any(v.value is UNDEFINED for _k, v in pairs):
                return VConst(UNDEFINED)
            return VConst(FrozenDict({k: v.value for k, v in pairs}))
        return VObj(pairs)
    if isinstance(plan, VArr):
        items = tuple(_bind_value(v, params) for v in plan.items)
        if all(isinstance(v, VConst) for v in items):
            if any(v.value is UNDEFINED for v in items):
                return VConst(UNDEFINED)
            return VConst(tuple(v.value for v in items))
        return VArr(items)
    if isinstance(plan, VCall):
        args = tuple(_bind_value(v, params) for v in plan.args)
        out = VCall(plan.path, args)
        if all(isinstance(v, VConst) for v in args):
            return VConst(_compile_valuefn(out)(None, None))
        if (
            plan.path == ("sprintf",)
            and len(args) == 2
            and isinstance(args[0], VConst)
            and isinstance(args[0].value, str)
            and isinstance(args[1], VArr)
        ):
            segs = _split_simple_fmt(args[0].value)
            if segs is not None and len(segs) == len(args[1].items) + 1:
                return VFmt(tuple(segs), args[1].items)
        return out
    if isinstance(plan, VBinOp):
        lhs = _bind_value(plan.lhs, params)
        rhs = _bind_value(plan.rhs, params)
        out = VBinOp(plan.op, lhs, rhs)
        if isinstance(lhs, VConst) and isinstance(rhs, VConst):
            return VConst(_compile_valuefn(out)(None, None))
        return out
    if isinstance(plan, VSetDiff):
        return VSetDiff(_bind_value(plan.left, params),
                        _bind_value(plan.right, params))
    if isinstance(plan, VKeySet):
        return plan
    if isinstance(plan, (VReviewRef, VSlotRef)):
        return plan
    raise _Dynamic()


def _compile_valuefn(plan):
    """A bound value plan -> closure fn(row, entity) -> frozen value
    (UNDEFINED propagates: any undefined input makes the whole
    violation-object binding fail, the interpreter's assignment-failure
    semantics).  Bind-time constant folding calls the same closures with
    (None, None), so the semantics exist exactly once."""
    if isinstance(plan, VConst):
        return _const_getter(plan.value)
    if isinstance(plan, VReviewRef):
        segs = plan.segs

        def review_ref(row, entity, _segs=segs):
            return row.scalar_frozen(_segs)

        return review_ref
    if isinstance(plan, VSlotRef):
        rel = plan.rel
        if rel:
            def slot_ref(row, entity, _rel=rel):
                if entity is None:
                    return UNDEFINED
                v = _get_rel(entity, _rel)
                return UNDEFINED if v is ABSENT else freeze(v)

            return slot_ref

        def slot_entity(row, entity):
            return UNDEFINED if entity is None else freeze(entity)

        return slot_entity
    if isinstance(plan, VKeySet):
        ip, rl, ex = plan.iter_paths, plan.rel, plan.exclude

        def keyset(row, entity, _ip=ip, _rl=rl, _ex=ex):
            return RSet(row.keyset(_ip, _rl, _ex))

        return keyset
    if isinstance(plan, VSetDiff):
        lf, rf = _compile_valuefn(plan.left), _compile_valuefn(plan.right)

        def setdiff(row, entity):
            left = lf(row, entity)
            right = rf(row, entity)
            if not isinstance(left, RSet) or not isinstance(right, RSet):
                return UNDEFINED
            return left.difference(right)

        return setdiff
    if isinstance(plan, VObj):
        cpairs = tuple((k, _compile_valuefn(v)) for k, v in plan.pairs)

        def obj(row, entity, _ps=cpairs):
            out = {}
            for k, fn in _ps:
                v = fn(row, entity)
                if v is UNDEFINED:
                    return UNDEFINED
                out[k] = v
            return FrozenDict(out)

        return obj
    if isinstance(plan, VArr):
        fns = tuple(_compile_valuefn(v) for v in plan.items)

        def arr(row, entity, _fns=fns):
            out = []
            for fn in _fns:
                v = fn(row, entity)
                if v is UNDEFINED:
                    return UNDEFINED
                out.append(v)
            return tuple(out)

        return arr
    if isinstance(plan, VFmt):
        from ..engine.value import format_value

        segs = plan.segments
        fns = tuple(_compile_valuefn(a) for a in plan.args)

        def fmt(row, entity, _segs=segs, _fns=fns):
            parts = [_segs[0]]
            for j, fn in enumerate(_fns):
                v = fn(row, entity)
                if v is UNDEFINED:
                    return UNDEFINED
                try:
                    parts.append(format_value(v))
                except TypeError:
                    return UNDEFINED
                parts.append(_segs[j + 1])
            return "".join(parts)

        return fmt
    if isinstance(plan, VCall):
        fn = bi.lookup(plan.path)
        argfns = tuple(_compile_valuefn(a) for a in plan.args)

        def call(row, entity, _fn=fn, _argfns=argfns):
            args = []
            for afn in _argfns:
                v = afn(row, entity)
                if v is UNDEFINED:
                    return UNDEFINED
                args.append(v)
            try:
                out = _fn(*args)
            except bi.BuiltinError:
                return UNDEFINED
            except (TypeError, ValueError, ZeroDivisionError):
                return UNDEFINED
            return freeze(out) if isinstance(out, (list, dict, set)) else out

        return call
    if isinstance(plan, VBinOp):
        from ..engine.interp import _apply_binop

        lf, rf = _compile_valuefn(plan.lhs), _compile_valuefn(plan.rhs)
        op = plan.op

        def binop(row, entity):
            a = lf(row, entity)
            if a is UNDEFINED:
                return UNDEFINED
            b = rf(row, entity)
            if b is UNDEFINED:
                return UNDEFINED
            return _apply_binop(op, a, b)

        return binop
    raise TypeError(plan)


def bind(prog: Optional[VProgram], policy, constraint: dict) -> Optional[BoundPlan]:
    """Bind a template's compiled plans to one constraint, or None when
    the template is ineligible (no program, inexact program, any clause
    without a message plan, or an inventory-reading policy)."""
    if prog is None or not prog.exact:
        return None
    plans = getattr(prog, "clause_plans", None)
    if not plans or len(plans) != len(prog.clauses) or any(
        p is None for p in plans
    ):
        return None
    if getattr(policy, "uses_inventory", False):
        return None
    from ..client.drivers import constraint_match_spec, constraint_parameters

    params = freeze(constraint_parameters(constraint))
    if not isinstance(params, FrozenDict):
        params = FrozenDict({})
    match = constraint_match_spec(constraint)
    out = BoundPlan(
        tier=STATIC,
        # PRESENCE semantics, like _cell_memoable: an empty selector ({})
        # still consults the mutable namespace cache at match time, so
        # the native re-check may only be skipped when the keys are
        # absent outright
        match_exact="labelSelector" not in match
        and "namespaceSelector" not in match,
    )
    try:
        for clause, cplan in zip(prog.clauses, plans):
            bc = BoundClause(slot_iter=clause.slot_iter)
            res_conds, slot_conds = [], []
            for cond in clause.conds:
                bound = _bind_cond(cond, params, prog)
                if _cond_uses_slot(cond):
                    slot_conds.append(bound)
                else:
                    res_conds.append(bound)
            bc.res_conds = tuple(res_conds)
            bc.slot_conds = tuple(slot_conds)
            res_guards, slot_guards = [], []
            for gplan in cplan.guards:
                bound_g = _bind_value(gplan, params)
                if isinstance(bound_g, VConst):
                    if bound_g.value is UNDEFINED:
                        # e.g. an assignment from a missing parameter:
                        # the clause can never fire for any row
                        bc.never = True
                    continue  # defined constant: no per-row risk
                gfn = _compile_valuefn(bound_g)
                if _value_uses_slot(bound_g):
                    slot_guards.append(gfn)
                else:
                    res_guards.append(gfn)
            bc.res_guards = tuple(res_guards)
            bc.slot_guards = tuple(slot_guards)
            obj = _bind_value(cplan.obj, params)
            if isinstance(obj, VConst):
                if obj.value is UNDEFINED:
                    # a message input is undefined for EVERY row (missing
                    # parameter): the clause can never produce a violation
                    bc.never = True
                else:
                    bc.obj_static = obj.value
            else:
                out.tier = SLOTS
                bc.obj_fn = _compile_valuefn(obj)
            if bc.slot_iter is not None:
                out.tier = SLOTS
            out.clauses.append(bc)
    except _Dynamic:
        return None
    return out


def _cond_uses_slot(node) -> bool:
    from .vexpr import _clause_uses_slot

    return _clause_uses_slot(node)


def _value_uses_slot(plan) -> bool:
    """True when a bound value plan reads the clause's slot entity."""
    if isinstance(plan, VSlotRef):
        return True
    if isinstance(plan, VObj):
        return any(_value_uses_slot(v) for _k, v in plan.pairs)
    if isinstance(plan, (VArr, VCall, VFmt)):
        items = plan.items if isinstance(plan, VArr) else plan.args
        return any(_value_uses_slot(v) for v in items)
    if isinstance(plan, VBinOp):
        return _value_uses_slot(plan.lhs) or _value_uses_slot(plan.rhs)
    if isinstance(plan, VSetDiff):
        return _value_uses_slot(plan.left) or _value_uses_slot(plan.right)
    return False


# ---------------------------------------------------------------------------
# bounded worker pool for the residual interpreter tail
# ---------------------------------------------------------------------------


class RenderPool:
    """Bounded DAEMON-thread pool draining interpreter-rendered cells
    (ThreadPoolExecutor's non-daemon workers would hold process exit and
    trip the test suite's leak detector).  The coordinator thread owns
    every shared-state mutation (memos, metrics); workers run pure
    per-cell evaluations, so the pool adds concurrency only where it is
    safe.  Sized small: the interpreter is GIL-bound, so the win is
    bounded overlap (native match, freeze) rather than parallel
    speedup."""

    _lock = threading.Lock()
    _queue = None
    _started = 0

    MIN_CELLS = int(os.environ.get("GK_RENDER_POOL_MIN", "16"))
    # how long one interpreter cell may run before the coordinator starts
    # logging that it is stuck (it keeps waiting — see map_ordered)
    STUCK_CELL_WARN_S = float(os.environ.get("GK_RENDER_STUCK_WARN_S", "30"))
    WORKERS = max(1, int(os.environ.get(
        "GK_RENDER_WORKERS", str(min(4, os.cpu_count() or 1))
    )))

    @classmethod
    def _ensure_workers(cls):
        if cls._started >= cls.WORKERS:
            return
        with cls._lock:
            if cls._queue is None:
                import queue

                # gklint: disable=unbounded-queue -- fed only by map_ordered
                # with the CURRENT render batch's interp-tail cells; drained
                # before the call returns, so depth is bounded by batch size
                cls._queue = queue.SimpleQueue()
            while cls._started < cls.WORKERS:
                t = threading.Thread(
                    target=cls._worker,
                    name=f"gk-render-{cls._started}",
                    daemon=True,
                )
                t.start()
                cls._started += 1

    @classmethod
    def _worker(cls):
        q = cls._queue
        while True:
            fn, slot, done = q.get()
            try:
                slot[0] = fn()
            except BaseException as e:  # re-raised on the coordinator
                slot[1] = e
            done.set()

    @classmethod
    def map_ordered(cls, fns: List) -> List:
        """Run thunks concurrently, return results in submission order.
        Exceptions re-raise in submission order (matching the serial
        loop's first-failure semantics).  Falls back to a serial loop
        below MIN_CELLS, where pool overhead would dominate."""
        if len(fns) < cls.MIN_CELLS:
            return [fn() for fn in fns]
        cls._ensure_workers()
        tasks = []
        for fn in fns:
            slot = [None, None]
            done = threading.Event()
            cls._queue.put((fn, slot, done))
            tasks.append((slot, done))
        out = []
        for slot, done in tasks:
            # the coordinator may be holding the driver lock (webhook
            # deny-path rendering) — parking unboundedly on one wedged
            # cell would wedge every admission behind it silently.  The
            # cell is an interpreter evaluation, normally microseconds;
            # keep waiting (killing a slow-but-progressing render would
            # break result completeness) but make a stuck one loud.
            # repo convention: <=0 means the warning is OFF (plain wait)
            # — never a zero-timeout busy-spin; and clamp tiny values so
            # a misconfigured threshold cannot log per-millisecond
            warn_s = cls.STUCK_CELL_WARN_S
            if warn_s <= 0:
                # warning OFF — still never a bare unbounded wait (the
                # analyzer's blocking-under-lock rule would rightly
                # flag it through the driver-lock callers): poll on a
                # long bound, silently
                while not done.wait(timeout=3600.0):
                    pass
            else:
                warn_s = max(1.0, warn_s)
                waited = 0.0
                while not done.wait(timeout=warn_s):
                    waited += warn_s
                    logging.getLogger("gatekeeper.renderplan").warning(
                        "render cell stuck for %.0fs in the interpreter "
                        "pool (driver lock may be held upstream)", waited,
                    )
            if slot[1] is not None:
                raise slot[1]
            out.append(slot[0])
        return out
