"""Background XLA compilation for the fused evaluation executable.

Template/constraint mutation bumps the driver's constraint-side epoch and
discards the fused executable; without this module the NEXT review or audit
blocks on re-trace + XLA compile (seconds — reference ingestion budget is
~ms, pkg/controller/constrainttemplate/stats_reporter.go:33-37 buckets
1ms-5s).  SURVEY.md §7 hard-part 3 prescribes the fix implemented here:
serve evaluations from the interpreter oracle (identical semantics — the
device mask is only ever a pruning over-approximation of it) while the
vectorize+jit runs in a background thread, then swap atomically.

Locking contract: the compile thread holds the driver lock only for the
host-side input build (packing, ms); the XLA trace+compile — the seconds —
runs with the lock RELEASED, so interpreter-path evaluations are never
starved.  A storm of N template ingests coalesces: only the latest epoch is
ever compiled.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import jax

# A minimal-but-valid AdmissionRequest probe: packing it exercises every
# review-side array and column extractor, so the warmed executable covers
# the smallest row bucket (8) that real micro-batches land in.
_PROBE_REVIEW = {
    "uid": "__gk_probe__",
    "kind": {"group": "", "version": "v1", "kind": "Pod"},
    "name": "__gk_probe__",
    "namespace": "default",
    "operation": "CREATE",
    "userInfo": {"username": "system:gk-probe"},
    "object": {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": "__gk_probe__",
            "namespace": "default",
            "labels": {"app": "__gk_probe__"},
        },
        "spec": {"containers": []},
    },
}


class AsyncCompiler:
    """Owns the background compile thread for one TpuDriver.

    ready()      -> the fused executable matches the driver's current epoch
    kick()       -> a mutation happened; (re)start compilation
    wait(t)      -> block until ready (audit path: throughput over latency)
    """

    def __init__(self, driver):
        self._driver = driver
        self._cond = threading.Condition()
        self._ready_epoch = driver._cs_epoch
        self._thread = None
        self._stopped = False

    # -- state ---------------------------------------------------------------

    def ready(self) -> bool:
        return self._ready_epoch == self._driver._cs_epoch

    def kick(self):
        with self._cond:
            if self._stopped:
                return
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="gk-async-compile", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()

    def wait(self, timeout: Optional[float] = 120.0) -> bool:
        """Block until the fused executable matches the live epoch.
        timeout=None waits indefinitely; the audit path instead uses the
        driver's bounded AUDIT_COMPILE_WAIT_S so pathological epoch churn
        can never wedge the audit loop permanently (driver.py:100-105).  A
        stopped compiler returns False immediately: the sync path is then
        the only one left."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self.ready():
                if self._stopped:
                    return False
                if deadline is None:
                    left = 0.05
                else:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                # bounded wait: the target epoch itself can move under us
                self._cond.wait(min(left, 0.05))
        return True

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    # -- compile loop --------------------------------------------------------

    # Debounce: wait for the epoch to hold still this long before tracing.
    # During a template-ingest storm every mutation bumps the epoch; eagerly
    # compiling each one keeps this thread perpetually TRACING — pure-Python
    # work that holds the GIL and measurably taxes concurrent admission
    # serving (the numpy serving path needs no executable, so there is
    # nothing to gain from compiling mid-storm).  Bounded so sustained
    # churn still compiles at least every DEBOUNCE_MAX_S.
    DEBOUNCE_S = 0.25
    DEBOUNCE_MAX_S = 10.0

    def _run(self):
        import time as _time

        d = self._driver
        while True:
            with self._cond:
                while self.ready() and not self._stopped:
                    self._cond.wait()
                if self._stopped:
                    return
            t0 = _time.monotonic()
            while _time.monotonic() - t0 < self.DEBOUNCE_MAX_S:
                epoch = d._cs_epoch
                with self._cond:
                    if self._stopped:
                        return
                    self._cond.wait(self.DEBOUNCE_S)
                if d._cs_epoch == epoch:
                    break  # settled
            epoch = d._cs_epoch
            try:
                self._compile_epoch(epoch)
            except Exception:
                # fail open: a broken background compile must not wedge
                # evaluation off-device forever — the synchronous path will
                # surface the error on the next direct call.  Logged loudly:
                # a persistently broken compile otherwise stays invisible
                # until it resurfaces as a blocking sync compile (advisor r2)
                logging.getLogger("gatekeeper_tpu.asynccompile").exception(
                    "background XLA compile failed for epoch %d; "
                    "falling open to the synchronous path", epoch,
                )
                with self._cond:
                    if d._cs_epoch == epoch:
                        self._ready_epoch = epoch
                        self._cond.notify_all()

    def epoch_lag(self) -> int:
        """Mutation epochs the compiled executable is behind the live
        constraint side (0 = current) — the compile_epoch_lag gauge's
        source (obs/compilestats.py)."""
        return max(self._driver._cs_epoch - self._ready_epoch, 0)

    def _compile_epoch(self, epoch: int):
        import time as _time

        d = self._driver
        t_start = _time.perf_counter()
        # host-side build under the driver lock (ms): constraint-side pack +
        # probe review pack + column extraction.  The produced arrays are
        # fresh locals (packing always allocates), safe to use un-locked.
        with d._lock:
            if d._cs_epoch != epoch:
                return  # superseded mid-storm; outer loop re-reads
            n_constraints = sum(len(v) for v in d.constraints.values())
            if n_constraints == 0:
                with self._cond:
                    self._ready_epoch = epoch
                    self._cond.notify_all()
                return
            fn, _ordered, rp, cp, cols, group_params, _crow = d._device_inputs(
                [dict(_PROBE_REVIEW)]
            )
            rows = len(rp.arrays["valid"])
            # the constraint-side cache key the inputs were packed for —
            # read under the lock; _dispatch must not key the device cache
            # on a LATER epoch a concurrent mutation may have created
            cs_key = (d._cs_epoch, d.interner.snapshot_size())
        # XLA trace + compile OUTSIDE the lock — the whole point.  Warm the
        # PACKED variant: compute_masks dispatches _packed_variant(fn), so
        # warming only the unpacked fused fn would leave the first real
        # review to pay the full synchronous compile anyway.
        out = d._dispatch(
            d._packed_variant(fn), rp.arrays, cp.arrays, cols, group_params,
            rows, cs_key=cs_key,
        )
        jax.block_until_ready(out)
        with self._cond:
            if d._cs_epoch == epoch:
                self._ready_epoch = epoch
                self._cond.notify_all()
        # per-epoch compile telemetry (obs/compilestats.py): the whole
        # warm dispatch's wall time (pack + trace + XLA compile + first
        # dispatch) attributed to this epoch, plus the backlog AFTER it
        # landed — per-executable cold/warm classification is recorded
        # separately by aot_jit inside the dispatch
        from ..metrics.catalog import COMPILE_M, record_stage
        from ..obs import compilestats

        epoch_s = _time.perf_counter() - t_start
        compilestats.record_compile("epoch", epoch_s, "async", epoch=epoch)
        record_stage(COMPILE_M, epoch_s, {"path": "epoch"})
        compilestats.record_epoch_lag(self.epoch_lag())
