"""Incremental host-serving constraint side: numpy-mode fused evaluation.

The admission-sized serving path.  The device (XLA) fused executable is
the throughput path — audits, streaming, big batches — but behind a
network relay a single-review dispatch costs a full RTT, and during a
template-ingest storm every epoch bump forces a constraint-side repack
(~tens of ms at 500 templates) plus, on structure changes, an XLA
retrace (seconds).  The reference never degrades under ingest (ms-scale
compile budget, pkg/controller/constrainttemplate/stats_reporter.go:33-37),
so neither may we.

This module keeps a SECOND packed constraint side that is:

- evaluated in numpy (EvalEnv(xp=np) + match_kernel(xp=np)): the same
  VExpr IR and match algebra as the device path — identical soundness
  contract (over-approximate mask, exact interpreter render) — with no
  trace, no compile, and no device round-trip.  At C=500, R<=8 a serve
  is ~1-3 ms of numpy.
- maintained INCREMENTALLY from the driver's constraint-side change log:
  one added/updated/removed constraint costs one single-row pack merged
  into growing per-group buffers, O(1) in the number of installed
  templates.  A mid-storm admission review therefore never pays a full
  repack, let alone a compile.

Group layout mirrors the device side: constraints batch by program
STRUCTURE (vexpr.VProgram.structure_key), so a template clone lands in
an existing group and evaluates through the same program node walk.
Constraints without a vectorized program evaluate match-only (their
mask over-approximates to the match, and the exact render filters).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .columns import T_UNDEF, extract_columns
from .interning import Interner
from .matchkernel import match_kernel
from .pack import PAD, pack_constraints, pack_reviews
from .params import pack_params
from .vexpr import EvalEnv, eval_program

# pad values for growing each match-side buffer (axis>=1 widening and
# new rows): must equal what pack_constraints writes into padding
_CS_PAD = {
    "kind_pairs": PAD,
    "has_ns": False,
    "ns_ids": PAD,
    "has_ex": False,
    "ex_ids": PAD,
    "scope": 0,
    "valid": False,
    "ls_ml": PAD,
    "ls_op": -1,
    "ls_key": PAD,
    "ls_vals": PAD,
    "ls_nvals": 0,
    "has_nssel": False,
    "nssel_ml": PAD,
    "ns_op": -1,
    "ns_key": PAD,
    "ns_vals": PAD,
    "ns_nvals": 0,
}

_MATCH_ONLY = "__match_only__"


def _bucket(n: int, minimum: int = 1) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


def _grow_to(arr: np.ndarray, shape: Tuple[int, ...], pad) -> np.ndarray:
    """Return an array of at least `shape` (bucketed per axis) containing
    `arr` at the origin and `pad` elsewhere."""
    target = tuple(
        _bucket(max(a, s)) for a, s in zip(arr.shape, shape)
    )
    if target == arr.shape:
        return arr
    out = np.full(target, pad, arr.dtype)
    out[tuple(slice(0, s) for s in arr.shape)] = arr
    return out


def _write_row(buf: np.ndarray, row: int, src: np.ndarray, pad) -> np.ndarray:
    """Assign src[0] (a 1-row packed array) into buf[row], widening buf's
    trailing axes as needed; returns (possibly reallocated) buf."""
    need = (row + 1,) + src.shape[1:]
    buf = _grow_to(buf, need, pad)
    if src.ndim == 1:
        buf[row] = src[0]
        return buf
    # clear the row to pad first: the incoming row may be narrower than
    # the buffer (e.g. fewer kind pairs than the widest constraint)
    buf[row] = pad
    buf[(row,) + tuple(slice(0, s) for s in src.shape[1:])] = src[0]
    return buf


class _Group:
    """One structure group: growing [cap, ...] buffers + row assignment."""

    def __init__(self, prog):
        self.prog = prog  # None for match-only
        self.names: List[Optional[Tuple[str, str]]] = []
        self.rowof: Dict[Tuple[str, str], int] = {}
        self.free: List[int] = []
        self.cs: Optional[Dict[str, np.ndarray]] = None
        # program-side buffers (None when prog is None)
        self.params: Dict[Tuple, Dict[str, np.ndarray]] = {}
        self.lits: Dict[Tuple, Dict[str, np.ndarray]] = {}
        self.elems: Dict[Tuple, Dict[str, np.ndarray]] = {}
        # pred_id -> [mat [U,vocab] uint8, idx [cap(,P)] int32]
        self.tables: Dict[int, list] = {}
        self.stacks: Dict[int, Dict[Tuple[str, str], int]] = {}
        self.table_vocab = 0  # real (unpadded) vocab the mats cover

    def nrows(self) -> int:
        return len(self.names)

    def _alloc_row(self) -> int:
        if self.free:
            return self.free.pop()
        self.names.append(None)
        return len(self.names) - 1

    def add(self, kind: str, name: str, constraint: dict,
            interner: Interner, pred_cache) -> None:
        row = self._alloc_row()
        self.names[row] = (kind, name)
        self.rowof[(kind, name)] = row

        cp1 = pack_constraints([constraint], interner)
        if self.cs is None:
            self.cs = {}
            for k, a in cp1.arrays.items():
                self.cs[k] = a.copy()
            # row 0 written by construction
        else:
            for k, a in cp1.arrays.items():
                self.cs[k] = _write_row(self.cs[k], row, a, _CS_PAD[k])

        if self.prog is None:
            return
        meta: dict = {}
        p1, e1, t1 = pack_params(
            [constraint], self.prog, interner, pred_cache, 1, meta_out=meta
        )
        for ppath, enc in p1.items():
            if ppath and ppath[0] == "__lit__":
                self.lits[ppath] = enc  # structure-constant, shared
                continue
            dst = self.params.setdefault(ppath, {})
            for k, a in enc.items():
                pad = self._scalar_pad(k)
                buf = dst.get(k)
                if buf is None:
                    buf = np.full(1, pad, a.dtype)
                dst[k] = _write_row(buf, row, a, pad)
        for ekey, enc in e1.items():
            dst = self.elems.get(ekey)
            if dst is None:
                self.elems[ekey] = {k: a.copy() for k, a in enc.items()}
                continue
            for k, a in enc.items():
                dst[k] = _write_row(dst[k], row, a, self._scalar_pad(k))
        self._merge_tables(t1, meta.get("stacks", {}), row, interner,
                           pred_cache)

    @staticmethod
    def _scalar_pad(field: str):
        if field == "tcode":
            return T_UNDEF
        if field == "sid":
            return Interner.MISSING
        if field == "mask":
            return False
        return 0  # num

    def _merge_tables(self, t1, stacks, row, interner, pred_cache):
        from .params import _PRED_FNS  # noqa: F401 (documents provenance)

        vocab = interner.snapshot_size()
        for pred_id, (mat1, idx1) in t1.items():
            stack1 = stacks.get(pred_id, {})
            entry = self.tables.get(pred_id)
            if entry is None:
                gstack: Dict[Tuple[str, str], int] = {}
                gmat = np.zeros((1, _bucket(vocab, 256)), np.uint8)
                gidx = np.zeros((1,) + idx1.shape[1:], np.int32)
                self.tables[pred_id] = entry = [gmat, gidx]
                self.stacks[pred_id] = gstack
            else:
                gstack = self.stacks[pred_id]
            gmat, gidx = entry
            # map local table rows -> global rows (0 stays the all-false row)
            remap = {0: 0}
            for key, lrow in stack1.items():
                grow_ = gstack.get(key)
                if grow_ is None:
                    grow_ = len(gstack) + 1
                    gstack[key] = grow_
                    if grow_ >= gmat.shape[0]:
                        gmat = _grow_to(
                            gmat, (grow_ + 1, gmat.shape[1]), 0
                        )
                    dense = pred_cache[key].dense()
                    n = min(len(dense), gmat.shape[1])
                    gmat[grow_, :n] = dense[:n]
                remap[lrow] = grow_
            idx_mapped = np.vectorize(
                lambda v: remap.get(int(v), 0), otypes=[np.int32]
            )(idx1) if idx1.size else idx1.astype(np.int32)
            gidx = _write_row(gidx, row, idx_mapped, 0)
            entry[0], entry[1] = gmat, gidx
        # NOT resetting table_vocab: freshly-added rows were filled from
        # dense() up to the CURRENT vocab (>= table_vocab), and existing
        # rows still cover table_vocab — the next refresh_tables pass
        # extends everything from there.  Resetting to 0 here made every
        # mid-storm serve rewrite all mats (an O(stack x vocab) tax).

    def remove(self, kind: str, name: str) -> bool:
        row = self.rowof.pop((kind, name), None)
        if row is None:
            return False
        self.names[row] = None
        self.free.append(row)
        if self.cs is not None and row < len(self.cs["valid"]):
            self.cs["valid"][row] = False
        return True

    def refresh_tables(self, interner: Interner, pred_cache) -> None:
        """Extend predicate mats to cover the current vocabulary (reviews
        intern new strings; PredicateTable grows incrementally)."""
        vocab = interner.snapshot_size()
        if vocab <= self.table_vocab:
            return
        for pred_id, entry in self.tables.items():
            gmat = entry[0]
            if vocab > gmat.shape[1]:
                gmat = _grow_to(gmat, (gmat.shape[0], vocab), 0)
                entry[0] = gmat
            for key, grow_ in self.stacks[pred_id].items():
                dense = pred_cache[key].dense()
                n = min(len(dense), gmat.shape[1])
                gmat[grow_, self.table_vocab:n] = dense[self.table_vocab:n]
        self.table_vocab = vocab

    def eval(self, rv_arrays, cols, R: int) -> Tuple[np.ndarray, np.ndarray]:
        """-> (mask [cap, R], autoreject [cap, R]) numpy bools."""
        match, autoreject = match_kernel(rv_arrays, self.cs, xp=np)
        match = np.asarray(match)
        if self.prog is None:
            return match, np.asarray(autoreject)
        cap = len(self.cs["valid"])
        keysets = {
            spec.key: cols[spec.key]["ids"]
            for spec in self.prog.column_specs
            if spec.kind == "keyset"
        }
        prog_cols = {
            spec.key: cols[spec.key]
            for spec in self.prog.column_specs
            if spec.kind != "keyset"
        }
        params = dict(self.params)
        params.update(self.lits)
        env = EvalEnv(
            prog_cols, params,
            {k: self._padded_elems(v, cap) for k, v in self.elems.items()},
            {pid: (e[0], self._pad_rows(e[1], cap, 0))
             for pid, e in self.tables.items()},
            keysets, cap, R, xp=np,
        )
        vmask = np.asarray(eval_program(self.prog, env))
        return match & vmask, np.asarray(autoreject)

    def _padded_elems(self, enc, cap):
        return {
            k: self._pad_rows(a, cap, self._scalar_pad(k))
            for k, a in enc.items()
        }

    def _pad_rows(self, a, cap, pad):
        if a.shape[0] >= cap:
            return a
        return _grow_to(a, (cap,) + a.shape[1:], pad)


class NpSide:
    """The incrementally-maintained host constraint side for one driver."""

    def __init__(self):
        self.groups: Dict[str, _Group] = {}
        self.loc: Dict[Tuple[str, str], str] = {}  # (kind, name) -> group key
        self.kind_group: Dict[str, str] = {}  # kind -> group key used
        self.last_epoch = -1
        self._union_specs: Optional[list] = None
        # per-epoch gather plan: [(group, out_positions, group_rows)] so
        # mask assembly is one fancy-index per group, not an O(C) Python
        # row-copy loop per review
        self._gather: Optional[Tuple[int, list]] = None

    # -- sync ----------------------------------------------------------------

    def sync(self, driver) -> None:
        """Bring the side up to date with the driver's constraint state by
        consuming the change log (caller holds the driver lock)."""
        if driver._cs_epoch == self.last_epoch:
            return
        if self.last_epoch < driver._cs_log_floor:
            self._rebuild(driver)
            return
        for epoch, kind, name in driver._cs_change_log:
            if epoch <= self.last_epoch:
                continue
            if name is None:
                self._apply_kind(driver, kind)
            else:
                self._apply_one(driver, kind, name)
        self.last_epoch = driver._cs_epoch

    def _rebuild(self, driver) -> None:
        self.groups.clear()
        self.loc.clear()
        self.kind_group.clear()
        self._union_specs = None
        for kind, by_name in driver.constraints.items():
            for name in by_name:
                self._apply_one(driver, kind, name)
        self.last_epoch = driver._cs_epoch

    def _group_key(self, driver, kind: str) -> str:
        prog = driver.programs.get(kind)
        return prog.structure_key() if prog else _MATCH_ONLY

    def _apply_kind(self, driver, kind: str) -> None:
        """Template-level change: the program (and so the group) may have
        changed — re-home every constraint of the kind."""
        for (k, n) in [key for key in self.loc if key[0] == kind]:
            self._remove(k, n)
        for name in driver.constraints.get(kind, {}):
            self._add(driver, kind, name)

    def _apply_one(self, driver, kind: str, name: str) -> None:
        cur = driver.constraints.get(kind, {}).get(name)
        self._remove(kind, name)
        if cur is not None:
            self._add(driver, kind, name)

    def _add(self, driver, kind: str, name: str) -> None:
        constraint = driver.constraints[kind][name]
        gkey = self._group_key(driver, kind)
        g = self.groups.get(gkey)
        if g is None:
            prog = driver.programs.get(kind)
            g = self.groups[gkey] = _Group(prog if gkey != _MATCH_ONLY
                                           else None)
            self._union_specs = None
        g.add(kind, name, constraint, driver.interner, driver.pred_cache)
        self.loc[(kind, name)] = gkey

    def _remove(self, kind: str, name: str) -> None:
        gkey = self.loc.pop((kind, name), None)
        if gkey is None:
            return
        g = self.groups.get(gkey)
        if g is not None:
            g.remove(kind, name)
            if not g.rowof:
                del self.groups[gkey]
                self._union_specs = None

    # -- serve ---------------------------------------------------------------

    def union_specs(self) -> list:
        if self._union_specs is None:
            seen = {}
            for g in self.groups.values():
                if g.prog is None:
                    continue
                for spec in g.prog.column_specs:
                    seen.setdefault(spec.key, spec)
            self._union_specs = list(seen.values())
        return self._union_specs

    def serve(self, driver, reviews: List[dict]):
        """-> (ordered, mask [C, R], autoreject [C, R]) with rows in
        sorted (kind, name) order — the compute_masks contract — or None
        when the side has nothing installed.  Caller holds the lock."""
        if not self.loc:
            return None
        rp = pack_reviews(
            reviews, driver.interner, driver.store.cached_namespace,
            bucket_rows=False,
        )
        R = len(rp.arrays["valid"])
        cols = extract_columns(
            reviews, self.union_specs(), driver.interner, R
        )
        # AFTER column extraction: extract_columns is what interns the
        # program-side strings (images, label values, ...); the predicate
        # mats must cover every id the gather below can see
        for g in self.groups.values():
            g.refresh_tables(driver.interner, driver.pred_cache)
        ordered = driver._ordered_constraints()
        C = len(ordered)
        plan = self._gather
        if plan is None or plan[0] != driver._cs_epoch:
            by_group: Dict[str, Tuple[list, list]] = {}
            for i, (kind, name, _c) in enumerate(ordered):
                gkey = self.loc.get((kind, name))
                if gkey is None:
                    continue  # sync raced a mutation; treat as no-match
                pos, rows_ = by_group.setdefault(gkey, ([], []))
                pos.append(i)
                rows_.append(self.groups[gkey].rowof[(kind, name)])
            plan = (driver._cs_epoch, [
                (gkey, np.asarray(pos, np.intp), np.asarray(rows_, np.intp))
                for gkey, (pos, rows_) in by_group.items()
            ])
            self._gather = plan
        mask = np.zeros((C, R), bool)
        rej = np.zeros((C, R), bool)
        for gkey, pos, rows_ in plan[1]:
            gm, gr = self.groups[gkey].eval(rp.arrays, cols, R)
            mask[pos] = gm[rows_, :R]
            rej[pos] = gr[rows_, :R]
        return ordered, mask, rej
