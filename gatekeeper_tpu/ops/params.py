"""Constraint-parameter packing for vectorized violation programs."""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from .columns import T_COMP, T_FALSE, T_NULL, T_NUM, T_STR, T_TRUE, T_UNDEF
from .interning import Interner, PredicateTable
from .vexpr import Lit, ParamElemRef, ParamRef, StrPred, VProgram

_PRED_FNS = {
    "startswith": lambda s, v: s.startswith(v),
    "endswith": lambda s, v: s.endswith(v),
    "contains": lambda s, v: v in s,
    "re_match": lambda s, v: re.search(v, s) is not None,
}


def _walk_params(constraint: dict, ppath: Tuple[str, ...]):
    spec = constraint.get("spec")
    cur = spec.get("parameters") if isinstance(spec, dict) else None
    for seg in ppath:
        if isinstance(cur, dict) and seg in cur:
            cur = cur[seg]
        else:
            return None, False
    return cur, True


def _encode_scalar(values: List, interner: Interner):
    n = len(values)
    tcode = np.zeros(n, np.int8)
    sid = np.full(n, Interner.MISSING, np.int32)
    num = np.zeros(n, np.float64)
    for i, (v, present) in enumerate(values):
        if not present:
            tcode[i] = T_UNDEF
        elif v is None:
            tcode[i] = T_NULL
        elif v is True:
            tcode[i] = T_TRUE
        elif v is False:
            tcode[i] = T_FALSE
        elif isinstance(v, str):
            tcode[i] = T_STR
            sid[i] = interner.intern(v)
        elif isinstance(v, (int, float)):
            tcode[i] = T_NUM
            num[i] = float(v)
        else:
            tcode[i] = T_COMP
    return {"tcode": tcode, "sid": sid, "num": num}


def _bucket(n: int, minimum: int = 1) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


def pack_params(
    constraints: List[dict],
    prog: VProgram,
    interner: Interner,
    pred_cache: Dict[Tuple[str, str], PredicateTable],
    rows: int,
    meta_out: Optional[dict] = None,
):
    """-> (params, elems, tables) for EvalEnv.  `rows` >= len(constraints)
    (padded rows read as undefined).  When `meta_out` is given, it receives
    {"stacks": {pred_id: {(pred, value): table row}}} — the incremental
    host side (ops/npside.py) needs the row identities to merge a single
    constraint's tables into its growing group buffers."""
    pad = [(None, False)] * (rows - len(constraints))

    params: Dict[Tuple, Dict[str, np.ndarray]] = {}
    for ppath in prog.param_scalars:
        vals = [_walk_params(c, ppath) for c in constraints] + pad
        params[ppath] = _encode_scalar(vals, interner)
    for s in prog.literals:
        params[("__lit__", s)] = _encode_scalar([(s, True)], interner)

    elems: Dict[Tuple, Dict[str, np.ndarray]] = {}
    elem_values: Dict[Tuple, List[List]] = {}
    for ppath, subpaths in prog.param_arrays:
        per_c: List[List] = []
        for c in constraints:
            v, ok = _walk_params(c, ppath)
            per_c.append(v if ok and isinstance(v, list) else [])
        per_c += [[] for _ in pad]
        elem_values[ppath] = per_c
        width = _bucket(max((len(x) for x in per_c), default=0), 1)
        mask = np.zeros((rows, width), bool)
        for i, xs in enumerate(per_c):
            mask[i, : len(xs)] = True
        subpaths = set(subpaths) | {()}
        for sub in subpaths:
            flat: List = []
            for xs in per_c:
                for j in range(width):
                    if j < len(xs):
                        v = xs[j]
                        for seg in sub:
                            v = v.get(seg) if isinstance(v, dict) else None
                            if v is None:
                                break
                        flat.append((v, True))
                    else:
                        flat.append((None, False))
            enc = _encode_scalar(flat, interner)
            enc = {k: a.reshape(rows, width) for k, a in enc.items()}
            enc["mask"] = mask
            elems[(ppath, sub)] = enc

    # string-predicate lookup tables (built after all interning above)
    tables: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for node in prog.str_preds:
        fn = _PRED_FNS[node.pred]

        def table_for(value) -> int:
            # returns index into this node's table stack; 0 = all-false
            if not isinstance(value, str):
                return 0
            key = (node.pred, value)
            if key not in pred_cache:
                pred_cache[key] = PredicateTable(
                    # bind via default args to avoid late-binding bugs
                    interner,
                    (lambda s, _f=fn, _v=value: _f(s, _v)),
                )
            uniq = stack.setdefault(key, len(stack) + 1)
            return uniq

        stack: Dict[Tuple[str, str], int] = {}
        if isinstance(node.rhs, Lit):
            idx = np.full(rows, table_for(node.rhs.value), np.int32)
        elif isinstance(node.rhs, ParamRef):
            idx = np.zeros(rows, np.int32)
            for i, c in enumerate(constraints):
                v, ok = _walk_params(c, node.rhs.ppath)
                idx[i] = table_for(v) if ok else 0
        elif isinstance(node.rhs, ParamElemRef):
            per_c = elem_values[node.rhs.ppath]
            width = elems[(node.rhs.ppath, ())]["mask"].shape[1]
            idx = np.zeros((rows, width), np.int32)
            for i, xs in enumerate(per_c):
                for j, v in enumerate(xs):
                    sv = v
                    for seg in node.rhs.subpath:
                        sv = sv.get(seg) if isinstance(sv, dict) else None
                    idx[i, j] = table_for(sv)
        else:
            raise ValueError("unsupported StrPred rhs")
        vocab = interner.snapshot_size()
        # bucket both table dims so compiled executables survive vocabulary
        # growth and new predicate values (shape-stable jit cache)
        mat = np.zeros((_bucket(len(stack) + 1), _bucket(vocab, 256)), np.uint8)
        for (pred, value), row in stack.items():
            mat[row, :vocab] = pred_cache[(pred, value)].dense()[:vocab]
        tables[node.pred_id] = (mat, idx)
        if meta_out is not None:
            meta_out.setdefault("stacks", {})[node.pred_id] = dict(stack)

    return params, elems, tables
