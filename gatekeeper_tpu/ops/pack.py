"""Columnar packing of reviews and constraints for the match kernels.

Everything string-valued goes through the global Interner; list-valued match
fields become padded id arrays with masks.  Padded dims are bucketed
(next power of two) so jitted kernel shapes stay stable across calls.

Exactness note: the device-side match may OVER-approximate in exotic cases
(non-string labels); every positive cell is re-checked host-side with the
exact native matcher before results are produced (ops/driver.py), so only
performance — never correctness — depends on tightness.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..target.match import _MISSING, _get, _is_ns, needs_autoreject  # type: ignore
from .interning import Interner

WILD = -1  # "*" wildcard in kind selectors
PAD = -2
UNDEF = -4  # undefined (missing field) sentinel for id columns


def _bucket(n: int, minimum: int = 1) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


def _intern_labels(interner: Interner, labels: Any, out: List):
    if not isinstance(labels, dict):
        return
    for k in sorted(labels.keys(), key=str):
        out.append((interner.intern_value(k), interner.intern_value(labels[k])))


# --------------------------------------------------------------------------
# Reviews
# --------------------------------------------------------------------------


@dataclass
class ReviewPack:
    n: int
    arrays: Dict[str, np.ndarray]
    reviews: List[dict]


def _pad_flat_pairs(flat: np.ndarray, counts: np.ndarray,
                    rows: int) -> np.ndarray:
    """[(total,2) flats + per-row counts] -> padded [rows, W, 2] int32."""
    n = len(counts)
    width = _bucket(int(counts.max()) if n else 0, 1)
    arr = np.full((rows, width, 2), PAD, np.int32)
    if len(flat):
        starts = np.cumsum(counts) - counts
        rows_idx = np.repeat(np.arange(n), counts)
        cols_idx = np.arange(len(flat)) - np.repeat(starts, counts)
        arr[rows_idx, cols_idx] = flat
    return arr


def _pack_reviews_native(native, reviews, interner, cached_namespace,
                         rows: int) -> Optional[Dict[str, np.ndarray]]:
    n = len(reviews)
    bufs = {
        "group": np.full(rows, UNDEF, np.int32),
        "kind": np.full(rows, UNDEF, np.int32),
        "ns_name": np.full(rows, UNDEF, np.int32),
        "ns_mode": np.zeros(rows, np.int8),
        "always": np.zeros(rows, bool),
        "ns_empty": np.zeros(rows, bool),
        "is_ns": np.zeros(rows, bool),
        "obj_empty": np.ones(rows, bool),
        "old_empty": np.ones(rows, bool),
        "autoreject": np.zeros(rows, bool),
        "valid": np.zeros(rows, bool),
    }
    out = native.pack_reviews_core(
        list(reviews), interner._ids, interner._strings, cached_namespace,
        bufs,
    )
    obj_flat, obj_counts, old_flat, old_counts, ns_flat, ns_counts = out
    bufs["obj_labels"] = _pad_flat_pairs(obj_flat, obj_counts, rows)
    bufs["old_labels"] = _pad_flat_pairs(old_flat, old_counts, rows)
    bufs["ns_labels"] = _pad_flat_pairs(ns_flat, ns_counts, rows)
    bufs["valid"][:n] = True
    return bufs


def pack_reviews(
    reviews: List[dict],
    interner: Interner,
    cached_namespace: Callable[[str], Optional[dict]],
    bucket_rows: bool = True,
) -> ReviewPack:
    n = len(reviews)
    rows = _bucket(n, 8) if bucket_rows else max(n, 1)

    from ..native import load as _load_native

    native = _load_native()
    if native is not None:
        arrays = _pack_reviews_native(
            native, reviews, interner, cached_namespace, rows
        )
        if arrays is not None:
            return ReviewPack(n=n, arrays=arrays, reviews=reviews)

    group = np.full(rows, UNDEF, np.int32)
    kind = np.full(rows, UNDEF, np.int32)
    ns_name = np.full(rows, UNDEF, np.int32)  # get_ns_name result
    always = np.zeros(rows, bool)  # always_match_ns_selectors
    ns_empty = np.zeros(rows, bool)  # namespace missing-or-empty
    is_ns = np.zeros(rows, bool)
    obj_empty = np.ones(rows, bool)
    old_empty = np.ones(rows, bool)
    ns_mode = np.zeros(rows, np.int8)  # 0 always-T, 1 ns labels, 2 uncached, 3 is_ns
    autoreject = np.zeros(rows, bool)
    valid = np.zeros(rows, bool)

    obj_lab: List[List] = []
    old_lab: List[List] = []
    ns_lab: List[List] = []

    for i, review in enumerate(reviews):
        valid[i] = True
        rkind = review.get("kind") if isinstance(review.get("kind"), dict) else {}
        g = rkind.get("group", _MISSING)
        k = rkind.get("kind", _MISSING)
        group[i] = interner.intern_value(g) if g is not _MISSING else UNDEF
        kind[i] = interner.intern_value(k) if k is not _MISSING else UNDEF
        isns = _is_ns(review.get("kind"))
        is_ns[i] = isns
        ns = _get(review, "namespace", "")
        ns_empty[i] = ns == ""
        always[i] = (not isns) and ns == ""

        # get_ns_name
        if isns:
            obj = _get(review, "object", _MISSING)
            meta = _get(obj, "metadata", _MISSING) if obj is not _MISSING else _MISSING
            nm = _get(meta, "name", _MISSING) if meta is not _MISSING else _MISSING
            ns_name[i] = interner.intern_value(nm) if nm is not _MISSING else UNDEF
        else:
            nm = _get(review, "namespace", _MISSING)
            ns_name[i] = interner.intern_value(nm) if nm is not _MISSING else UNDEF

        obj = _get(review, "object", {})
        old = _get(review, "oldObject", {})
        obj_empty[i] = obj == {}
        old_empty[i] = old == {}
        ol: List = []
        _intern_labels(interner, _get(_get(obj, "metadata", {}), "labels", {}), ol)
        obj_lab.append(ol)
        odl: List = []
        _intern_labels(interner, _get(_get(old, "metadata", {}), "labels", {}), odl)
        old_lab.append(odl)

        # namespaceSelector resolution mode
        nsl: List = []
        if isns:
            ns_mode[i] = 3
        elif always[i]:
            ns_mode[i] = 0
        else:
            unstable_ns = _get(_get(review, "_unstable", {}), "namespace", _MISSING)
            ns_obj = unstable_ns if unstable_ns is not _MISSING else None
            if ns_obj is None and isinstance(ns, str):
                ns_obj = cached_namespace(ns)
            if ns_obj is None:
                ns_mode[i] = 2
            else:
                ns_mode[i] = 1
                _intern_labels(
                    interner, _get(_get(ns_obj, "metadata", {}), "labels", {}), nsl
                )
        ns_lab.append(nsl)

        autoreject[i] = needs_autoreject(
            {"spec": {"match": {"namespaceSelector": {}}}}, review, cached_namespace
        )

    def pad_pairs(rows_pairs: List[List], rows_total: int) -> np.ndarray:
        width = _bucket(max((len(p) for p in rows_pairs), default=0), 1)
        arr = np.full((rows_total, width, 2), PAD, np.int32)
        for i, pairs in enumerate(rows_pairs):
            for j, (a, b) in enumerate(pairs):
                arr[i, j] = (a, b)
        return arr

    arrays = {
        "group": group,
        "kind": kind,
        "ns_name": ns_name,
        "always": always,
        "ns_empty": ns_empty,
        "is_ns": is_ns,
        "obj_empty": obj_empty,
        "old_empty": old_empty,
        "ns_mode": ns_mode,
        "autoreject": autoreject,
        "valid": valid,
        "obj_labels": pad_pairs(obj_lab, rows),
        "old_labels": pad_pairs(old_lab, rows),
        "ns_labels": pad_pairs(ns_lab, rows),
    }
    return ReviewPack(n=n, arrays=arrays, reviews=reviews)


# --------------------------------------------------------------------------
# Constraints
# --------------------------------------------------------------------------

OP_CODES = {"In": 0, "NotIn": 1, "Exists": 2, "DoesNotExist": 3}
OP_UNKNOWN = 4
SCOPE_CODES = {"*": 1, "Namespaced": 2, "Cluster": 3}
SCOPE_NONE = 0
SCOPE_OTHER = 4


@dataclass
class ConstraintPack:
    n: int
    arrays: Dict[str, np.ndarray]
    constraints: List[dict]


def _pack_selector(selector: Any, interner: Interner):
    """-> (matchLabels pairs, exprs list of (op, key_id, value_ids))."""
    if not isinstance(selector, dict) or selector is None:
        selector = {}
    pairs: List = []
    ml = _get(selector, "matchLabels", {})
    if isinstance(ml, dict):
        for k in sorted(ml.keys(), key=str):
            pairs.append((interner.intern_value(k), interner.intern_value(ml[k])))
    exprs = []
    me = _get(selector, "matchExpressions", [])
    if isinstance(me, list):
        for e in me:
            if not isinstance(e, dict):
                # original indexes operator/key -> undefined -> no clause fires
                continue
            op = OP_CODES.get(e.get("operator"), OP_UNKNOWN)
            key = interner.intern_value(e.get("key"))
            values = _get(e, "values", [])
            vids = (
                [interner.intern_value(v) for v in values]
                if isinstance(values, list)
                else []
            )
            exprs.append((op, key, vids))
    return pairs, exprs


def pack_constraints(constraints: List[Optional[dict]], interner: Interner) -> ConstraintPack:
    """None entries are PAD rows (valid=False, match never fires): the
    driver lays constraints out group-major with per-group padded blocks
    so the fused update per group is a static slice."""
    n = len(constraints)
    rows = _bucket(n, 1)

    kind_pairs: List[List] = []
    ns_lists: List[List] = []
    ex_lists: List[List] = []
    has_ns = np.zeros(rows, bool)
    has_ex = np.zeros(rows, bool)
    scope = np.zeros(rows, np.int8)
    has_nssel = np.zeros(rows, bool)
    valid = np.zeros(rows, bool)

    sel_ml: List[List] = []
    sel_ex: List[List] = []
    nssel_ml: List[List] = []
    nssel_ex: List[List] = []

    for i, c in enumerate(constraints):
        if c is None:  # pad row: valid stays False, empty lists below
            kind_pairs.append([])
            ns_lists.append([])
            ex_lists.append([])
            sel_ml.append([])
            sel_ex.append([])
            nssel_ml.append([])
            nssel_ex.append([])
            continue
        valid[i] = True
        match = _get(_get(c, "spec", {}), "match", {})
        if not isinstance(match, dict):
            match = {}

        kinds = _get(match, "kinds", [{"apiGroups": ["*"], "kinds": ["*"]}])
        pairs: List = []
        if isinstance(kinds, list):
            for ks in kinds:
                if not isinstance(ks, dict):
                    continue
                groups = ks.get("apiGroups") or []
                names = ks.get("kinds") or []
                gids = [
                    WILD if g == "*" else interner.intern_value(g) for g in groups
                ]
                kids = [
                    WILD if k == "*" else interner.intern_value(k) for k in names
                ]
                for g in gids:
                    for k in kids:
                        pairs.append((g, k))
        kind_pairs.append(pairs)

        has_ns[i] = "namespaces" in match
        nss = match.get("namespaces")
        ns_lists.append(
            [interner.intern_value(x) for x in nss] if isinstance(nss, list) else []
        )
        has_ex[i] = "excludedNamespaces" in match
        exs = match.get("excludedNamespaces")
        ex_lists.append(
            [interner.intern_value(x) for x in exs] if isinstance(exs, list) else []
        )

        if "scope" not in match:
            scope[i] = SCOPE_NONE
        else:
            scope[i] = SCOPE_CODES.get(match.get("scope"), SCOPE_OTHER)

        ml, ex = _pack_selector(_get(match, "labelSelector", {}), interner)
        sel_ml.append(ml)
        sel_ex.append(ex)

        has_nssel[i] = "namespaceSelector" in match
        nml, nex = _pack_selector(_get(match, "namespaceSelector", {}), interner)
        nssel_ml.append(nml)
        nssel_ex.append(nex)

    def pad_pairs2(rows_pairs: List[List]) -> np.ndarray:
        width = _bucket(max((len(p) for p in rows_pairs), default=0), 1)
        arr = np.full((rows, width, 2), PAD, np.int32)
        for i, pairs in enumerate(rows_pairs):
            for j, pr in enumerate(pairs):
                arr[i, j] = pr
        return arr

    def pad_ids(rows_ids: List[List]) -> np.ndarray:
        width = _bucket(max((len(p) for p in rows_ids), default=0), 1)
        arr = np.full((rows, width), PAD, np.int32)
        for i, ids in enumerate(rows_ids):
            arr[i, : len(ids)] = ids
        return arr

    def pad_exprs(rows_exprs: List[List]):
        e_width = _bucket(max((len(e) for e in rows_exprs), default=0), 1)
        v_width = _bucket(
            max((len(v) for e in rows_exprs for (_o, _k, v) in e), default=0), 1
        )
        op = np.full((rows, e_width), -1, np.int8)
        key = np.full((rows, e_width), PAD, np.int32)
        vals = np.full((rows, e_width, v_width), PAD, np.int32)
        nvals = np.zeros((rows, e_width), np.int32)
        for i, exprs in enumerate(rows_exprs):
            for j, (o, k, v) in enumerate(exprs):
                op[i, j] = o
                key[i, j] = k
                vals[i, j, : len(v)] = v
                nvals[i, j] = len(v)
        return op, key, vals, nvals

    ls_op, ls_key, ls_vals, ls_nvals = pad_exprs(sel_ex)
    ns_op, ns_key, ns_vals, ns_nvals = pad_exprs(nssel_ex)

    arrays = {
        "kind_pairs": pad_pairs2(kind_pairs),
        "has_ns": has_ns,
        "ns_ids": pad_ids(ns_lists),
        "has_ex": has_ex,
        "ex_ids": pad_ids(ex_lists),
        "scope": scope,
        "valid": valid,
        "ls_ml": pad_pairs2(sel_ml),
        "ls_op": ls_op,
        "ls_key": ls_key,
        "ls_vals": ls_vals,
        "ls_nvals": ls_nvals,
        "has_nssel": has_nssel,
        "nssel_ml": pad_pairs2(nssel_ml),
        "ns_op": ns_op,
        "ns_key": ns_key,
        "ns_vals": ns_vals,
        "ns_nvals": ns_nvals,
    }
    return ConstraintPack(n=n, arrays=arrays, constraints=constraints)
