"""Ahead-of-time executable cache: serialized compiled XLA programs.

SURVEY.md §5.4: all engine state is derived and rebuilt on boot; the one
artifact worth keeping across restarts is the compiled evaluation
program.  jax's persistent compilation cache (ops/xlacache.py) already
skips the XLA *compile*, but a restarted process still re-TRACES every
fused function (pure Python, seconds for a 500-template corpus) before
the cache can even be consulted — measured as the dominant share of cold
start.  This module serializes the whole compiled executable
(jax.experimental.serialize_executable) keyed by the trace-equivalence
signature + concrete input layout, so a warm restart skips trace AND
compile: deserialize is ~ms.

Scope and safety:
- Keys include the jax version, backend kind, a fingerprint of this
  package's kernel SOURCE (an executable serialized by an older build
  must never serve a binary whose kernel semantics changed), and a hash
  of the structure signature plus every input leaf's shape/dtype — any
  mismatch is a miss and the caller falls back to the normal jit path.
- Entries are pickles, and unpickling attacker-supplied bytes is code
  execution: the cache directory is created 0700 and every entry is
  sealed with the shared HMAC scheme (util/seal.py — the same trust
  model the snapshot manifest uses, documented in docs/snapshots.md).
  An entry whose seal does not verify is dropped and treated as a
  miss BEFORE any pickle byte is parsed.
- Single-device executables only (the mesh path's device assignment
  does not survive a process restart; it stays on the jit path).
- A deserialized executable that rejects its args is deleted and its
  key blacklisted, so a bad entry costs one reload, not one per call.
- XLA:CPU AOT results are machine-feature-pinned: restoring on a
  different host may refuse or warn — also treated as a miss.  The
  production restart scenario is the same pod image on the same node.

The wrapper (aot_jit) mimics the narrow jit surface the driver uses:
call with concrete arrays, get outputs; no static/donated args.
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import pickle
import threading
from typing import Any, Callable, Optional

import jax

log = logging.getLogger("gatekeeper.aotcache")

_dir: Optional[str] = None
_lock = threading.Lock()
# read-mostly consumer mode (docs/fleet.md trust model): fleet webhook
# replicas SHARE the cache dir with the rest of the fleet.  They may add
# entries (atomic rename, additive) but must never delete shared ones —
# a replica on a newer code fingerprint sees every older build's seal
# fail, and auto-dropping would strip the warmth the still-running old
# replicas restore from.
_read_mostly = False


def _record_cache(cache: str, hit: bool):
    """Observability counters, isolated so a metrics problem can never
    break the compile path."""
    try:
        from ..metrics.catalog import record_cache

        record_cache(cache, hit)
    except Exception:  # pragma: no cover - metrics must never block eval
        log.debug("cache metric recording failed", exc_info=True)


def _record_compile(seconds: float, path: str):
    try:
        from ..metrics.catalog import COMPILE_M, record_stage

        record_stage(COMPILE_M, seconds, {"path": path})
    except Exception:  # pragma: no cover
        log.debug("compile metric recording failed", exc_info=True)


def _cost_analysis(compiled):
    """(flops, bytes_accessed) from XLA's cost model, when this jax
    build exposes it — (None, None) otherwise.  Never raises."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return None, None
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        return (
            float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None,
        )
    except Exception:
        return None, None


def enable(cache_dir: str, read_mostly: Optional[bool] = None) -> bool:
    global _dir, _read_mostly
    try:
        from ..util import seal as _seal

        _seal.secure_makedirs(cache_dir)
    except OSError:
        log.exception("aot cache dir unavailable: %s", cache_dir)
        return False
    _dir = cache_dir
    if read_mostly is None:
        read_mostly = os.environ.get("GK_AOT_READ_MOSTLY", "") not in (
            "", "0", "false",
        )
    _read_mostly = bool(read_mostly)
    return True


def enabled() -> bool:
    return _dir is not None


def _code_fingerprint() -> str:
    """Digest of every source file in this package (shared with the
    snapshot manifest — util/seal.py): a build whose kernel code changed
    must never reuse an older build's executables (they would silently
    reproduce pre-fix semantics)."""
    from ..util.seal import code_fingerprint

    return code_fingerprint()


# sealed-entry framing: one hex HMAC line, then the pickle payload
_SEAL_HEADER_LEN = 64


def _seal_entry(payload: bytes) -> bytes:
    from ..util import seal as _seal

    return _seal.seal(payload).encode("ascii") + b"\n" + payload


def _open_sealed(blob: bytes) -> Optional[bytes]:
    """Payload bytes iff the seal verifies; None otherwise (including
    pre-seal legacy entries, which are simply re-written on next save)."""
    if len(blob) < _SEAL_HEADER_LEN + 1 or blob[_SEAL_HEADER_LEN] != 0x0A:
        return None
    from ..util import seal as _seal

    tag = blob[:_SEAL_HEADER_LEN].decode("ascii", "replace")
    payload = blob[_SEAL_HEADER_LEN + 1:]
    if not _seal.verify(payload, tag):
        return None
    return payload


def _leaf_sig(x) -> str:
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return f"{tuple(x.shape)}:{x.dtype}"
    return f"py:{type(x).__name__}:{x!r}"


def load(key: str):
    """-> compiled executable or None."""
    if _dir is None:
        return None
    path = os.path.join(_dir, key + ".aot")
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        return None
    except Exception:
        log.exception("aot cache entry unreadable: %s", key)
        return None
    payload_bytes = _open_sealed(blob)
    if payload_bytes is None:
        # unauthenticated bytes are never unpickled; drop the entry so
        # the cost is one miss, and the next save re-writes it sealed
        log.warning("aot cache entry failed seal verification "
                    "(dropped, treated as miss): %s", key)
        drop(key)
        return None
    try:
        payload, in_tree, out_tree = pickle.loads(payload_bytes)
    except Exception:
        log.exception("aot cache entry undecodable: %s", key)
        return None
    try:
        from jax.experimental import serialize_executable as se

        return se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:
        log.warning("aot cache entry failed to load (treated as miss): %s",
                    key)
        return None


def save(key: str, compiled) -> bool:
    if _dir is None:
        return False
    try:
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = se.serialize(compiled)
        buf = io.BytesIO()
        pickle.dump((payload, in_tree, out_tree), buf,
                    protocol=pickle.HIGHEST_PROTOCOL)
        path = os.path.join(_dir, key + ".aot")
        # pid AND thread id: two threads of one process saving the same
        # key (e.g. review + audit shapes compiling concurrently) must
        # not interleave writes into one tmp file
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(_seal_entry(buf.getvalue()))
        os.replace(tmp, path)  # atomic: concurrent writers race benignly
        return True
    except Exception:
        log.exception("aot cache save failed: %s", key)
        return False


def drop(key: str) -> None:
    """Remove one entry — unless this process is a read-mostly consumer
    of a SHARED dir, where a locally-unusable entry (stale seal, host
    mismatch) is someone else's warmth: it stays, and the local miss is
    the whole cost."""
    if _dir is None or _read_mostly:
        return
    try:
        os.remove(os.path.join(_dir, key + ".aot"))
    except OSError:
        pass


class aot_jit:
    """jit with executable persistence.

    First call per input layout: try the AOT cache (deserialize, ~ms);
    miss -> lower+compile via the normal jit machinery and persist the
    executable.  Executables are memoized per layout key (one aot_jit
    instance serves multiple shape buckets — admission batches and the
    audit-capacity shape — without thrashing); a key whose executable
    rejects its args is blacklisted and its file dropped.
    """

    def __init__(self, fn: Callable, tag: str, sig: Any = None):
        self._fn = fn
        self._jitted = jax.jit(fn)
        self._tag = tag
        # the expensive, per-instance-constant key components hash once
        h = hashlib.sha256()
        h.update(jax.__version__.encode())
        h.update(_code_fingerprint().encode())
        h.update(tag.encode())
        h.update(repr(sig).encode())
        self._prefix = h
        self._compiled: dict = {}  # key -> executable
        self._validated: set = set()  # keys whose output was block-checked
        self._bad: set = set()
        self._mu = threading.Lock()
        # jax.jit attribute parity for wrappers that reach for it
        self.__wrapped__ = fn

    def _key(self, args) -> str:
        h = self._prefix.copy()
        h.update(jax.default_backend().encode())
        leaves, treedef = jax.tree_util.tree_flatten(args)
        h.update(str(treedef).encode())
        for leaf in leaves:
            h.update(_leaf_sig(leaf).encode())
        return f"{self._tag}-{h.hexdigest()[:32]}"

    def __call__(self, *args):
        if not enabled():
            return self._jitted(*args)  # tests/no-cache: plain jit
        key = self._key(args)
        with self._mu:
            compiled = self._compiled.get(key)
            bad = key in self._bad
            validated = key in self._validated
        if compiled is None and not bad:
            import time as _time

            from ..obs import compilestats

            t_load = _time.perf_counter()
            compiled = load(key)
            if compiled is not None:
                log.info("aot cache hit: %s", key)
                _record_cache("aotcache", True)
                # provenance telemetry: an AOT deserialize is the cheap
                # restart path — /debug/compilez attributes cold start
                # between it, persistent-cache compiles and cold compiles
                compilestats.record_compile(
                    self._tag, _time.perf_counter() - t_load, "aot",
                )
            else:
                _record_cache("aotcache", False)
                # one trace+compile for this layout (the .compile()
                # consults jax's persistent XLA cache when enabled), then
                # persist the executable so the NEXT process skips the
                # trace too
                from ..obs import trace as obstrace

                xla_hits0 = compilestats.get_stats().xla_counters()[0]
                t0 = _time.perf_counter()
                compiled = self._jitted.lower(*args).compile()
                t1 = _time.perf_counter()
                obstrace.record_span(
                    "xla.compile", t0, t1, stage=obstrace.COMPILE,
                    tag=self._tag,
                )
                _record_compile(t1 - t0, self._tag)
                # cold vs persistent-cache-warm: jax's monitoring counters
                # tick during .compile() when the persistent cache
                # answered; without the counters the split is unknowable
                # (ops/xlacache.py exports that absence explicitly)
                stats = compilestats.get_stats()
                if stats.xla_counters_available:
                    prov = (
                        "persistent"
                        if stats.xla_counters()[0] > xla_hits0 else "cold"
                    )
                else:
                    prov = "unknown"
                flops, nbytes = _cost_analysis(compiled)
                compilestats.record_compile(
                    self._tag, t1 - t0, prov,
                    flops=flops, bytes_accessed=nbytes,
                )
                save(key, compiled)
                with self._mu:
                    self._validated.add(key)  # it just compiled here
            with self._mu:
                self._compiled[key] = compiled
        if compiled is not None:
            try:
                out = compiled(*args)
                if not validated:
                    # dispatch is ASYNC: a deserialized executable that
                    # cannot run on this host (XLA:CPU AOT results are
                    # machine-feature-pinned) fails at block time, which
                    # would otherwise surface far from here in the
                    # caller's fetch.  Validate loaded entries once.
                    jax.block_until_ready(out)
                    with self._mu:
                        self._validated.add(key)
                return out
            except Exception:
                # layout drift, loader refusal, or a host-incompatible
                # executable: drop the entry and blacklist the key so the
                # cost is one reload, not per call.  The jit fallback
                # below re-runs the work; it is BLOCKED here so a failure
                # that was never about this executable (e.g. a transient
                # device OOM) still surfaces at the call site rather than
                # asynchronously in the caller's fetch — blacklisting a
                # healthy entry on such a failure costs one re-trace, a
                # deliberate trade against serving a broken executable.
                log.warning("aot executable rejected args; blacklisting "
                            "and falling back to jit: %s", key)
                drop(key)
                with self._mu:
                    self._compiled.pop(key, None)
                    self._validated.discard(key)
                    self._bad.add(key)
                out = self._jitted(*args)
                jax.block_until_ready(out)
                return out
        return self._jitted(*args)
