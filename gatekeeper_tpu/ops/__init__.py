"""TPU execution backend: columnar packing, vectorized match/violation
kernels, and the TpuDriver.

Design (SURVEY.md section 2.4 / 7):
- The audit sweep constraints x resources becomes one batched boolean-tensor
  evaluation on device; admission reviews micro-batch onto the same kernels.
- Violation predicates compiled from the Rego AST may OVER-approximate
  (never under-): positive cells are re-rendered through the interpreter
  oracle, so false positives cost host render time, never correctness.
- Templates outside the vectorizable fragment fall back to all-true masks
  (pure interpreter evaluation for their cells).
"""
