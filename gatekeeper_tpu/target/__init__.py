from .match import constraint_matches, needs_autoreject, matches_label_selector  # noqa: F401
from .target import K8sValidationTarget, AugmentedReview, AugmentedUnstructured, WipeData  # noqa: F401
