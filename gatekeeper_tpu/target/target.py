"""K8sValidationTarget — the target adapter (reference pkg/target/target.go).

Owns: data-path layout for replicated cluster state, review shaping
(unstructured objects / admission requests / augmented reviews -> the
gkReview JSON the policies see), violation-resource rehydration, and the
constraint `match` schema.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass
from typing import Any, Optional, Tuple
from urllib.parse import quote, unquote


class TargetError(Exception):
    pass


class WipeData:
    """Sentinel: remove all replicated data (target.go:36-41)."""


@dataclass
class AugmentedUnstructured:
    """An object plus its (optional) Namespace for nsSelector matching
    (target.go:52-56)."""

    object: dict
    namespace: Optional[dict] = None


@dataclass
class AugmentedReview:
    """An AdmissionRequest plus its (optional) Namespace (target.go:43-46)."""

    admission_request: dict
    namespace: Optional[dict] = None


class K8sValidationTarget:
    name = "admission.k8s.gatekeeper.sh"  # target.go:27-29

    # ---- data layout ------------------------------------------------------

    def process_data(self, obj: Any) -> Tuple[bool, Tuple[str, ...], Any]:
        """Map an object to its inventory path (target.go:62-89):
        cluster/<groupVersion>/<kind>/<name> or
        namespace/<ns>/<groupVersion>/<kind>/<name>.
        Returns (handled, path_segments, data)."""
        if isinstance(obj, WipeData) or obj is WipeData:
            return True, (), None
        if not isinstance(obj, dict):
            return False, (), None
        api = obj.get("apiVersion") or ""
        kind = obj.get("kind") or ""
        meta = obj.get("metadata") or {}
        name = meta.get("name") or ""
        if not api:
            raise TargetError(f"resource {name} has no version")
        if not kind:
            raise TargetError(f"resource {name} has no kind")
        ns = meta.get("namespace") or ""
        if ns == "":
            return True, ("cluster", api, kind, name), obj
        return True, ("namespace", ns, api, kind, name), obj

    def path_string(self, segments: Tuple[str, ...]) -> str:
        """External (Driver-interface) path form with the groupVersion
        URL-escaped, as the reference does with url.PathEscape."""
        return "/".join(quote(s, safe="") for s in segments)

    @staticmethod
    def parse_path(path: str) -> Tuple[str, ...]:
        return tuple(unquote(s) for s in path.split("/"))

    # ---- review shaping ---------------------------------------------------

    def handle_review(self, obj: Any) -> Tuple[bool, Optional[dict]]:
        """Shape any accepted input into the gkReview JSON document
        (target.go:91-127).  Returns (handled, review_dict)."""
        if isinstance(obj, AugmentedReview):
            review = dict(obj.admission_request)
            if obj.namespace:
                review["_unstable"] = {"namespace": obj.namespace}
            return True, review
        if isinstance(obj, AugmentedUnstructured):
            review = self._unstructured_to_request(obj.object)
            if obj.namespace is not None:
                review["_unstable"] = {"namespace": obj.namespace}
                ns_name = (obj.namespace.get("metadata") or {}).get("name")
                if ns_name:
                    review["namespace"] = ns_name
            return True, review
        if isinstance(obj, dict):
            if self._is_admission_request(obj):
                return True, dict(obj)
            if "apiVersion" in obj and "kind" in obj:
                return True, self._unstructured_to_request(obj)
        return False, None

    @staticmethod
    def _is_admission_request(obj: dict) -> bool:
        # An AdmissionRequest has a structured kind {group, version, kind}.
        k = obj.get("kind")
        return isinstance(k, dict) and "kind" in k

    @staticmethod
    def _unstructured_to_request(obj: dict) -> dict:
        api = obj.get("apiVersion") or ""
        if "/" in api:
            group, version = api.split("/", 1)
        else:
            group, version = "", api
        return {
            "kind": {"group": group, "version": version, "kind": obj.get("kind", "")},
            "name": (obj.get("metadata") or {}).get("name", ""),
            "object": obj,
        }

    @staticmethod
    def make_audit_review(
        obj: dict, api_version: str, kind: str, name: str, namespace: str = ""
    ) -> dict:
        """make_review / add_field for cached-state audits
        (target_template_source.go:47-90)."""
        if "/" in api_version:
            group, version = api_version.split("/", 1)
        else:
            group, version = "", api_version
        review = {
            "kind": {"group": group, "version": version, "kind": kind},
            "name": name,
            "object": obj,
        }
        if namespace:
            review["namespace"] = namespace
        return review

    # ---- violation rehydration -------------------------------------------

    def handle_violation(self, review: dict) -> dict:
        """Rebuild the violating object from its review (target.go:193-244)."""
        kind = review.get("kind") or {}
        group = kind.get("group")
        version = kind.get("version")
        k = kind.get("kind")
        if not isinstance(group, str) or not isinstance(version, str) or not isinstance(k, str):
            raise TargetError(f"bad review kind: {json.dumps(kind)[:200]}")
        api_version = version if group == "" else f"{group}/{version}"
        obj = review.get("object")
        if not isinstance(obj, dict) or obj is None:
            obj = review.get("oldObject")
        if not isinstance(obj, dict):
            raise TargetError("no object or oldObject returned in review")
        out = copy.deepcopy(obj)
        out["apiVersion"] = api_version
        out["kind"] = k
        return out

    # ---- match schema -----------------------------------------------------

    def match_schema(self) -> dict:
        """The constraint spec.match schema (target.go:246-318)."""
        string_list = {"type": "array", "items": {"type": "string"}}
        label_selector = {
            "type": "object",
            "properties": {
                "matchLabels": {
                    "type": "object",
                    "additionalProperties": {"type": "string"},
                },
                "matchExpressions": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "key": {"type": "string"},
                            "operator": {
                                "type": "string",
                                "enum": ["In", "NotIn", "Exists", "DoesNotExist"],
                            },
                            "values": string_list,
                        },
                    },
                },
            },
        }
        return {
            "type": "object",
            "properties": {
                "kinds": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "apiGroups": string_list,
                            "kinds": string_list,
                        },
                    },
                },
                "namespaces": string_list,
                "excludedNamespaces": string_list,
                "labelSelector": label_selector,
                "namespaceSelector": label_selector,
                "scope": {"type": "string", "enum": ["*", "Cluster", "Namespaced"]},
            },
        }
