"""Native constraint-match semantics.

This is a faithful, vectorization-friendly re-implementation of the
reference's Rego matching library (pkg/target/target_template_source.go,
generated from pkg/target/regolib/src.rego): kind selectors, namespaces,
excludedNamespaces, labelSelector, namespaceSelector, scope, and the
namespace-not-cached autoreject rule.  Its behavior — including the
undefined-propagation quirks of the original Rego — is pinned by a
differential test that runs the original library source through the
gatekeeper_tpu interpreter (tests/test_match_differential.py).

`None` field values are treated as missing, per get_default
(target_template_source.go:107-125).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

_MISSING = object()


def _get(obj: Any, field: str, default=_MISSING):
    """get_default semantics: missing key or null -> default."""
    if not isinstance(obj, dict):
        return default
    v = obj.get(field, _MISSING)
    if v is _MISSING or v is None:
        return default
    return v


def _is_ns(kind: Any) -> bool:
    # target_template_source.go:289-292
    return (
        isinstance(kind, dict)
        and kind.get("group") == ""
        and kind.get("kind") == "Namespace"
    )


def _always_match_ns_selectors(review: dict) -> bool:
    # :316-319 — cluster-scoped resources (empty/missing namespace) that are
    # not themselves Namespaces skip all namespace-based selectors.
    return not _is_ns(review.get("kind")) and _get(review, "namespace", "") == ""


def _get_ns_name(review: dict):
    # :303-311; returns _MISSING when undefined in the Rego original.
    if _is_ns(review.get("kind")):
        obj = _get(review, "object", _MISSING)
        if obj is _MISSING:
            return _MISSING
        meta = _get(obj, "metadata", _MISSING)
        if meta is _MISSING:
            return _MISSING
        return _get(meta, "name", _MISSING)
    return _get(review, "namespace", _MISSING)


def _kind_selector_matches(match: dict, review: dict) -> bool:
    # :131-156
    kinds = _get(match, "kinds", [{"apiGroups": ["*"], "kinds": ["*"]}])
    if not isinstance(kinds, list):
        return False
    kind = review.get("kind") if isinstance(review.get("kind"), dict) else {}
    group = kind.get("group", _MISSING)
    k = kind.get("kind", _MISSING)
    for ks in kinds:
        if not isinstance(ks, dict):
            continue
        groups = ks.get("apiGroups") or []
        names = ks.get("kinds") or []
        g_ok = "*" in groups or (group is not _MISSING and group in groups)
        k_ok = "*" in names or (k is not _MISSING and k in names)
        if g_ok and k_ok:
            return True
    return False


def _matches_scope(match: dict, review: dict) -> bool:
    # :162-180 — uses has_field, so a null/false-valued "scope" counts as
    # PRESENT (unlike get_default) and then matches nothing.
    if "scope" not in match:
        return True
    scope = match.get("scope")
    if scope == "*":
        return True
    ns = _get(review, "namespace", "")
    if scope == "Namespaced":
        return ns != ""
    if scope == "Cluster":
        return ns == ""
    return False


def _match_expression_violated(op: str, labels: dict, key: Any, values: list) -> bool:
    # :186-211 — undefined bodies in the original simply don't fire.  The
    # original's has_field treats a null-valued key as PRESENT.
    has = isinstance(labels, dict) and key in labels
    val = labels.get(key) if has else None
    if op == "In":
        if not has:
            return True
        return len(values) > 0 and val not in values
    if op == "NotIn":
        return has and len(values) > 0 and val in values
    if op == "Exists":
        return not has
    if op == "DoesNotExist":
        return has
    return False  # unknown operator: no violated-rule clause fires


def matches_label_selector(selector: Any, labels: Any) -> bool:
    # :216-230
    if not isinstance(selector, dict):
        selector = {}
    if not isinstance(labels, dict):
        labels = {}
    match_labels = _get(selector, "matchLabels", {})
    if isinstance(match_labels, dict):
        for k, v in match_labels.items():
            # matchLabels[key] == labels[key]: a missing label key is
            # undefined (never satisfied), even against a null selector value.
            if k not in labels or labels[k] != v:
                return False
    exprs = _get(selector, "matchExpressions", [])
    if isinstance(exprs, list):
        for e in exprs:
            if not isinstance(e, dict):
                # original indexes operator/key and gets undefined: not violated
                continue
            op = e.get("operator")
            key = e.get("key")
            values = _get(e, "values", [])
            if not isinstance(values, list):
                values = []
            if _match_expression_violated(op, labels, key, values):
                return False
    return True


def _any_labelselector_match(selector: Any, review: dict) -> bool:
    # :233-278 — empty object and missing object are equivalent.
    obj = _get(review, "object", {})
    old = _get(review, "oldObject", {})
    obj_empty = obj == {}
    old_empty = old == {}

    def labels_of(o):
        return _get(_get(o, "metadata", {}), "labels", {})

    if obj_empty and old_empty:
        return matches_label_selector(selector, {})
    if old_empty:
        return matches_label_selector(selector, labels_of(obj))
    if obj_empty:
        return matches_label_selector(selector, labels_of(old))
    return matches_label_selector(selector, labels_of(obj)) or matches_label_selector(
        selector, labels_of(old)
    )


def _matches_namespaces(match: dict, review: dict) -> bool:
    # :321-337 — has_field semantics: null/false-valued "namespaces" counts
    # as present; the set comprehension over it is then empty.
    if "namespaces" not in match:
        return True
    if _always_match_ns_selectors(review):
        return True
    ns = _get_ns_name(review)
    if ns is _MISSING:
        return False
    nss = match.get("namespaces")
    return isinstance(nss, list) and ns in nss


def _does_not_match_excluded(match: dict, review: dict) -> bool:
    # :339-355 — same has_field presence semantics as _matches_namespaces.
    if "excludedNamespaces" not in match:
        return True
    if _always_match_ns_selectors(review):
        return True
    ns = _get_ns_name(review)
    if ns is _MISSING:
        return False
    nss = match.get("excludedNamespaces")
    return not (isinstance(nss, list) and ns in nss)


def _matches_nsselector(
    match: dict, review: dict, cached_namespace: Callable[[str], Optional[dict]]
) -> bool:
    # :357-380 — gated on has_field (null counts present); the selector value
    # itself then goes through get_default (null -> {} matches everything).
    if "namespaceSelector" not in match:
        return True
    selector = _get(match, "namespaceSelector", {})
    if _is_ns(review.get("kind")):
        return _any_labelselector_match(selector, review)
    if _always_match_ns_selectors(review):
        return True
    # get_ns (:294-301): side-loaded namespace first, then the cached one.
    ns_obj = _get(_get(review, "_unstable", {}), "namespace", _MISSING)
    if ns_obj is _MISSING:
        ns_name = _get(review, "namespace", _MISSING)
        cached = cached_namespace(ns_name) if ns_name is not _MISSING else None
        if cached is None:
            return False
        ns_obj = cached
    nslabels = _get(_get(ns_obj, "metadata", {}), "labels", {})
    return matches_label_selector(selector, nslabels)


def constraint_matches(
    constraint: dict,
    review: dict,
    cached_namespace: Callable[[str], Optional[dict]] = lambda name: None,
) -> bool:
    """matching_constraints (target_template_source.go:27-44) for one
    constraint against one review."""
    match = _get(_get(constraint, "spec", {}), "match", {})
    if not isinstance(match, dict):
        match = {}
    return (
        _kind_selector_matches(match, review)
        and _matches_namespaces(match, review)
        and _does_not_match_excluded(match, review)
        and _matches_nsselector(match, review, cached_namespace)
        and _matches_scope(match, review)
        and _any_labelselector_match(_get(match, "labelSelector", {}), review)
    )


def needs_autoreject(
    constraint: dict,
    review: dict,
    cached_namespace: Callable[[str], Optional[dict]] = lambda name: None,
) -> bool:
    """autoreject_review (target_template_source.go:12-25): a constraint with
    a namespaceSelector autorejects when the review's namespace is neither
    side-loaded (_unstable.namespace) nor cached.  Faithfully preserves the
    original's undefined-propagation: a review with *no* namespace field also
    autorejects (absent namespace makes `namespace == ""` undefined, so
    `not namespace == ""` succeeds)."""
    match = _get(_get(constraint, "spec", {}), "match", {})
    if not isinstance(match, dict) or "namespaceSelector" not in match:
        return False
    ns_name = _get(review, "namespace", _MISSING)
    if ns_name is not _MISSING and not isinstance(ns_name, str):
        ns_name = _MISSING
    if ns_name is not _MISSING and cached_namespace(ns_name) is not None:
        return False
    # `not input.review._unstable.namespace`: any defined non-false value
    # blocks autoreject (null included); false or missing lets it through.
    unstable = review.get("_unstable")
    if isinstance(unstable, dict) and "namespace" in unstable:
        if unstable["namespace"] is not False:
            return False
    if ns_name == "":
        return False
    return True
