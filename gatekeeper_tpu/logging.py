"""Structured logging with the reference's stable keys
(pkg/logging/logging.go:3-22) over stdlib logging, JSON-rendered.

Violation/deny events from the webhook and audit manager log through
`log_event` with these keys so downstream tooling can parse them the same
way it parses the reference's zap output.
"""

from __future__ import annotations

import json
import logging
import sys
import time

# logging.go:3-22 — stable structured keys
PROCESS = "process"
DETAILS = "details"
EVENT_TYPE = "event_type"
TEMPLATE_NAME = "template_name"
CONSTRAINT_GROUP = "constraint_group"
CONSTRAINT_API_VERSION = "constraint_api_version"
CONSTRAINT_KIND = "constraint_kind"
CONSTRAINT_NAME = "constraint_name"
CONSTRAINT_NAMESPACE = "constraint_namespace"
CONSTRAINT_ACTION = "constraint_action"
AUDIT_ID = "audit_id"
CONSTRAINT_STATUS = "constraint_status"
RESOURCE_GROUP = "resource_group"
RESOURCE_API_VERSION = "resource_api_version"
RESOURCE_KIND = "resource_kind"
RESOURCE_NAMESPACE = "resource_namespace"
RESOURCE_NAME = "resource_name"
REQUEST_USERNAME = "request_username"
# observability addition: every structured event carries the active trace
# id when one exists, so a deny log line correlates with its
# /debug/traces entry (and the upstream traceparent)
TRACE_ID = "trace_id"


# level encoders, matching zapcore's set (reference main.go:74-79)
_ANSI = {"debug": "\x1b[35m", "info": "\x1b[34m", "warning": "\x1b[33m",
         "error": "\x1b[31m", "critical": "\x1b[31m"}
LEVEL_ENCODERS = {
    "lower": lambda lv: lv.lower(),
    "capital": lambda lv: lv.upper(),
    "color": lambda lv: f"{_ANSI.get(lv.lower(), '')}{lv.lower()}\x1b[0m",
    "capitalcolor": lambda lv: f"{_ANSI.get(lv.lower(), '')}{lv.upper()}\x1b[0m",
}


class JsonFormatter(logging.Formatter):
    def __init__(self, level_key: str = "level", level_encoder: str = "lower"):
        super().__init__()
        self.level_key = level_key
        self.level_encoder = LEVEL_ENCODERS[level_encoder]

    def format(self, record: logging.LogRecord) -> str:
        out = {
            self.level_key: self.level_encoder(record.levelname),
            "ts": time.time(),  # wall-clock: ok (log record timestamp)
            "logger": record.name,
            "msg": record.getMessage(),
        }
        extra = getattr(record, "kv", None)
        if extra:
            out.update(extra)
        if record.exc_info:
            out["error"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup(
    level: str = "INFO",
    stream=None,
    level_key: str = "level",
    level_encoder: str = "lower",
) -> logging.Logger:
    """Process-wide JSON logger (the reference's zap setup, main.go:121-136;
    --log-level-key / --log-level-encoder mirror main.go:84-85)."""
    if level_encoder not in LEVEL_ENCODERS:
        raise ValueError(f"invalid log level encoder: {level_encoder}")
    root = logging.getLogger("gatekeeper")
    root.setLevel(level.upper())
    if not root.handlers:
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(JsonFormatter(level_key, level_encoder))
        root.addHandler(h)
        root.propagate = False
    else:
        # re-setup (second App in one process, flag-configured key/encoder
        # after a default setup): apply the new format to the existing
        # handler instead of silently keeping the old one
        root.handlers[0].setFormatter(JsonFormatter(level_key, level_encoder))
    return root


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"gatekeeper.{name}")


def log_event(logger: logging.Logger, msg: str, level: int = logging.INFO, **kv):
    """Structured log line with stable keys (e.g. violation_audited,
    admission deny — reference policy.go:241-257, audit/manager.go:732-750).
    The active trace id (obs.trace context) is injected automatically so
    violation/deny lines correlate with their trace."""
    if TRACE_ID not in kv:
        tid = _current_trace_id()
        if tid is not None:
            kv[TRACE_ID] = tid
    logger.log(level, msg, extra={"kv": kv})


# imported last: obs.trace depends only on the stdlib, so this cannot
# cycle back into this module
from .obs.trace import current_trace_id as _current_trace_id  # noqa: E402
