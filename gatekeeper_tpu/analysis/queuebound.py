"""Unbounded-queue lint (rule: unbounded-queue) — ISSUE 12.

The congestive-collapse recipe is always the same: a serving path
accepts work faster than it can finish it, and the buffer between the
two grows without bound until latency (then memory) dies.  The overload
plane bounds the repo's serving queues (micro-batcher ``max_pending``,
the front door's per-backend inflight cap); this pass keeps them
bounded — and keeps NEW queues from shipping unbounded by default:

unbounded-queue   (1) any ``queue.Queue()`` / ``SimpleQueue()``
                  constructed without a positive ``maxsize`` —
                  repo-wide, because an unbounded channel is a latent
                  collapse point wherever it sits.  By-design unbounded
                  sites (the watch event pump, the replica command
                  demux) carry reasoned inline suppressions, which is
                  exactly the documentation they were missing.
                  (2) on SERVING-PATH modules (webhook/, fleet/): a
                  ``self.<name> = []`` attribute whose name says it is a
                  queue (pending/backlog/queue) with no visible bound —
                  no ``len(self.<name>)`` comparison anywhere in the
                  class.  The list the micro-batcher queues requests on
                  is the exact object that grew without bound before
                  ISSUE 12.

The list heuristic is deliberately scoped to the serving tree: a
scratch list named ``pending`` in the audit packer is bounded by its
input; the same list on the admission path is bounded by nothing but
client patience.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, Project, register_pass, register_rule

R_UNBOUNDED_QUEUE = register_rule(
    "unbounded-queue",
    "a queue with no bound on (or near) a serving path — the congestive-"
    "collapse buffer; give it a maxsize / len() bound or a reasoned "
    "suppression",
)

# queue constructors that take maxsize (Queue/LifoQueue/PriorityQueue)
# or are unbounded by construction (SimpleQueue)
_SIZED_QUEUE_CTORS = ("Queue", "LifoQueue", "PriorityQueue")
_UNSIZED_QUEUE_CTORS = ("SimpleQueue",)

# serving-path prefixes for the list-attribute heuristic
_SERVING_PREFIXES = (
    "gatekeeper_tpu/webhook/",
    "gatekeeper_tpu/fleet/",
)

# attribute names that declare queue intent
_QUEUEY_NAMES = ("pending", "backlog", "queue")


def _dotted(expr: ast.expr) -> Optional[str]:
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _queue_ctor_kind(call: ast.Call) -> Optional[str]:
    """'sized' for Queue-family ctors, 'unsized' for SimpleQueue, None
    for anything else.  Matches both bare names (from queue import
    Queue) and dotted ones (queue.Queue, _queue.Queue)."""
    d = _dotted(call.func)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    if leaf in _SIZED_QUEUE_CTORS:
        return "sized"
    if leaf in _UNSIZED_QUEUE_CTORS:
        return "unsized"
    return None


def _has_positive_maxsize(call: ast.Call) -> bool:
    """True when the ctor passes a maxsize that is not literally 0
    (queue.Queue treats 0 / negative as infinite; a non-constant value
    is given the benefit of the doubt — the bound exists, its value is
    config)."""
    candidates: List[ast.expr] = []
    if call.args:
        candidates.append(call.args[0])
    for kw in call.keywords:
        if kw.arg == "maxsize":
            candidates.append(kw.value)
    for c in candidates:
        if isinstance(c, ast.Constant):
            if isinstance(c.value, (int, float)) and c.value > 0:
                return True
            continue  # literal 0/None: explicitly unbounded
        return True  # computed bound: accept
    return False


def _self_attr_of_len_compare(node: ast.Compare) -> List[str]:
    """self-attribute names appearing inside len(self.X) on either side
    of a comparison — the visible-bound evidence."""
    out: List[str] = []
    for side in [node.left, *node.comparators]:
        for sub in ast.walk(side):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"
                and sub.args
            ):
                d = _dotted(sub.args[0])
                if d and d.startswith("self."):
                    out.append(d[len("self."):])
    return out


def _is_queuey(name: str) -> bool:
    low = name.lower()
    return any(q in low for q in _QUEUEY_NAMES)


@register_pass
def queuebound_pass(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None:
            continue

        # ---- (1) queue.Queue() without a positive maxsize, repo-wide --------
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _queue_ctor_kind(node)
            if kind is None:
                continue
            d = _dotted(node.func) or "Queue"
            if kind == "unsized":
                findings.append(mod.finding(
                    R_UNBOUNDED_QUEUE, node.lineno,
                    f"{d}() is unbounded by construction — use a "
                    "maxsize-bounded Queue (or justify with a reasoned "
                    "suppression)",
                ))
            elif not _has_positive_maxsize(node):
                findings.append(mod.finding(
                    R_UNBOUNDED_QUEUE, node.lineno,
                    f"{d}() without a positive maxsize is an unbounded "
                    "buffer — the congestive-collapse shape; bound it "
                    "or justify with a reasoned suppression",
                ))

        # ---- (2) list-backed pending queues on serving paths ----------------
        if not any(mod.relpath.startswith(p) for p in _SERVING_PREFIXES):
            continue
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            # attr name -> first assignment line of a list literal
            listy: dict = {}
            bounded: set = set()
            for sub in ast.walk(cls):
                target = None
                value = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    target, value = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    target, value = sub.target, sub.value
                if target is not None and isinstance(value, ast.List):
                    d = _dotted(target)
                    if d and d.startswith("self."):
                        attr = d[len("self."):]
                        if _is_queuey(attr):
                            listy.setdefault(attr, sub.lineno)
                if isinstance(sub, ast.Compare):
                    bounded.update(_self_attr_of_len_compare(sub))
            for attr, lineno in sorted(listy.items()):
                if attr in bounded:
                    continue
                findings.append(mod.finding(
                    R_UNBOUNDED_QUEUE, lineno,
                    f"{cls.name}.{attr} is a list-backed queue on a "
                    "serving path with no visible bound (no "
                    f"len(self.{attr}) comparison in the class) — cap "
                    "it like MicroBatcher.max_pending or justify with "
                    "a reasoned suppression",
                ))
    return findings
