"""Resource-hygiene lints (rules: thread-leak, bare-join, listener-close,
start-guard).

The conftest leak fixture catches these at RUNTIME (a leaked non-daemon
thread hangs pytest; a leaked listener holds its port); these rules catch
the same classes statically, before a test has to die for them:

thread-leak     every `threading.Thread(...)` must either be
                `daemon=True` or be joined somewhere in the same file
                (a `stop()`-style owner).  A non-daemon thread nobody
                joins pins process exit forever.
bare-join       `t.join()` with no timeout waits unboundedly — a wedged
                worker (the PR 8 wedge chaos class) then hangs shutdown.
                Join with a timeout and check `is_alive()` after
                (util.join_thread does both).  Zero-argument `.join()`
                is reliably a thread join: `str.join` always takes the
                iterable argument.
listener-close  a class that binds a socketserver listener must tear it
                down via util.close_listener / server_close somewhere in
                the same file — the idempotent-start contract
                (WebhookServer, MetricsExporter, HealthServer...).
start-guard     a `start()` method that creates a thread or listener
                must be idempotent: guard on (or tear down) the previous
                instance first.  A double start otherwise leaks the old
                thread/socket — the exact bug fixed on WebhookServer
                (PR 3), HealthServer/ProfileServer (PR 7).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, Module, Project, register_pass, register_rule

R_THREAD_LEAK = register_rule(
    "thread-leak",
    "a threading.Thread is neither daemon=True nor joined in this file",
)
R_BARE_JOIN = register_rule(
    "bare-join",
    "thread join without a timeout — a wedged thread hangs shutdown; "
    "use util.join_thread (join with timeout + liveness check)",
)
R_LISTENER = register_rule(
    "listener-close",
    "a socketserver listener is bound but never closed in this file "
    "(util.close_listener / server_close)",
)
R_START_GUARD = register_rule(
    "start-guard",
    "start() creates a thread/listener without guarding against a "
    "previous live one — a double start leaks it",
)

_THREAD_CTORS = ("threading.Thread", "_threading.Thread", "Thread")
_LISTENER_CTORS = (
    "ThreadingHTTPServer", "HTTPServer", "TCPServer", "UDPServer",
    "socketserver.TCPServer", "socketserver.ThreadingTCPServer",
    "http.server.ThreadingHTTPServer",
)


def _dotted(expr: ast.expr) -> Optional[str]:
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _kw(node: ast.Call, name: str) -> Optional[ast.expr]:
    for k in node.keywords:
        if k.arg == name:
            return k.value
    return None


def _thread_name(node: ast.Call) -> str:
    nm = _kw(node, "name")
    if isinstance(nm, ast.Constant) and isinstance(nm.value, str):
        return f" ({nm.value!r})"
    return ""


@register_pass
def hygiene_pass(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        src = mod.source
        # thread-join detection must be AST-shaped like bare-join's:
        # a `.join` attribute call with zero positional args (str.join
        # always takes its iterable, os.path.join several) — a raw
        # substring test would let `", ".join(names)` anywhere in the
        # file silently disable thread-leak for the whole module.
        # join_thread(t, timeout, ...) is the util helper equivalent.
        has_join = False
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            if (
                isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                and not n.args
            ):
                has_join = True
                break
            fname = getattr(n.func, "id", getattr(n.func, "attr", ""))
            if fname == "join_thread":
                has_join = True
                break
        closes_listener = (
            "close_listener" in src or "server_close" in src
        )

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)

            # ---- thread-leak ------------------------------------------------
            if d in _THREAD_CTORS:
                daemon = _kw(node, "daemon")
                is_daemon = (
                    isinstance(daemon, ast.Constant) and daemon.value is True
                )
                if not is_daemon and not has_join:
                    findings.append(mod.finding(
                        R_THREAD_LEAK, node.lineno,
                        "Thread" + _thread_name(node) + " is not "
                        "daemon=True and nothing in this file joins a "
                        "thread — it outlives (or hangs) process exit",
                    ))

            # ---- bare-join --------------------------------------------------
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not node.args
                and not node.keywords
            ):
                findings.append(mod.finding(
                    R_BARE_JOIN, node.lineno,
                    f"`{_dotted(node.func) or 'thread.join'}()` without a "
                    "timeout — a wedged thread hangs the caller forever; "
                    "join with a timeout and handle is_alive() "
                    "(util.join_thread)",
                ))

            # ---- listener-close ---------------------------------------------
            if d is not None and (
                d in _LISTENER_CTORS
                or d.split(".")[-1] in ("ThreadingHTTPServer", "HTTPServer")
            ):
                if not closes_listener:
                    findings.append(mod.finding(
                        R_LISTENER, node.lineno,
                        f"{d} bound here but this file never closes a "
                        "listener (util.close_listener / server_close) — "
                        "the port leaks across restarts",
                    ))

        # ---- start-guard ----------------------------------------------------
        for cls_node in ast.walk(mod.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            for fn in cls_node.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if fn.name not in ("start", "start_monitor", "serve"):
                    continue
                created: List[str] = []  # self-attrs assigned a thread/server
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call
                    ):
                        d = _dotted(sub.value.func) or ""
                        if d in _THREAD_CTORS or d in _LISTENER_CTORS or (
                            d.split(".")[-1] in (
                                "Thread", "ThreadingHTTPServer", "HTTPServer",
                            )
                        ):
                            for tgt in sub.targets:
                                td = _dotted(tgt)
                                if td and td.startswith("self."):
                                    created.append(td)
                if not created:
                    continue
                # guarded iff the method TESTS one of those attrs (an If
                # or a boolean/compare expression referencing it) before
                # or around creating the new one, or tears the old one
                # down via close_listener/shutdown/is_alive
                fn_src_names = set()
                for sub in ast.walk(fn):
                    if isinstance(sub, (ast.If, ast.IfExp)):
                        for name in ast.walk(sub.test):
                            dd = _dotted(name) if isinstance(
                                name, (ast.Attribute, ast.Name)
                            ) else None
                            if dd:
                                fn_src_names.add(dd)
                    if isinstance(sub, ast.Call):
                        dd = _dotted(sub.func) or ""
                        if dd.endswith("close_listener") or dd.endswith(
                            ".shutdown"
                        ) or dd.endswith(".is_alive"):
                            fn_src_names.add("__teardown__")
                guarded = "__teardown__" in fn_src_names or any(
                    attr in n or n in attr
                    for attr in created for n in fn_src_names
                )
                if not guarded:
                    findings.append(mod.finding(
                        R_START_GUARD, fn.lineno,
                        f"{cls_node.name}.{fn.name}() creates "
                        f"{', '.join(sorted(set(created)))} without "
                        "checking for a previous live one — a double "
                        "start leaks the old thread/listener (idempotent-"
                        "start contract, docs/static-analysis.md)",
                    ))
    return findings
