"""JAX trace-safety lints (rules: tracer-truthiness, jit-in-loop,
impure-in-jit).

Inside a `jax.jit` / `shard_map` region the array arguments are tracers:

* Python truthiness (`if x:`, `while x:`, `assert x`) and scalar
  coercion (`bool()`/`float()`/`int()`) on a traced value raise
  `TracerBoolConversionError`/`ConcretizationTypeError` at trace time —
  or worse, silently bake in a branch when the value is concrete during
  tests but traced in production (`tracer-truthiness`).
* Constructing a jit wrapper inside a loop recompiles (or at minimum
  re-hashes and cache-probes) every iteration; jit objects belong at
  module/closure scope (`jit-in-loop`).
* Wall-clock and RNG calls inside a compiled region execute ONCE at
  trace time and then freeze into the executable — a seeded
  `np.random` draw or `time.time()` stamp inside a kernel is a latent
  staleness bug (`impure-in-jit`).

Jitted regions are found syntactically: `@jax.jit` / `@jit` /
`@partial(jax.jit, ...)` decorators, `g = jax.jit(f)` /
`shard_map(f, ...)` wrapping of a function defined in the same module,
and inline `jax.jit(lambda ...)`.  Truthiness tracking is a single
forward pass: parameters seed the tainted set, assignments propagate it,
and shape-space accessors (`.shape`, `.ndim`, `.dtype`, `len()`) kill
it, since those are static under tracing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, Module, Project, register_pass, register_rule

R_TRUTHY = register_rule(
    "tracer-truthiness",
    "Python truthiness or bool/int/float() on a traced value inside a "
    "jit/shard_map region",
)
R_JIT_LOOP = register_rule(
    "jit-in-loop",
    "jax.jit(...) constructed inside a loop — hoist the wrapper out",
)
R_IMPURE = register_rule(
    "impure-in-jit",
    "wall-clock/RNG call inside a compiled region freezes at trace time",
)

# attribute accesses that are static under tracing (shape space)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}
_SCALARIZERS = {"bool", "float", "int", "complex"}
_IMPURE_DOTTED = (
    "time.time", "time.monotonic", "time.perf_counter", "_time.time",
    "_time.monotonic", "_time.perf_counter", "datetime.now",
    "datetime.datetime.now", "random.random", "random.randint",
    "random.choice", "random.shuffle", "np.random", "numpy.random",
)


def _dotted(expr: ast.expr) -> Optional[str]:
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_callable(func: ast.expr) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) as a callable expression."""
    d = _dotted(func)
    if d in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return True
    if isinstance(func, ast.Call):
        fd = _dotted(func.func)
        if fd in ("partial", "functools.partial") and func.args:
            return _is_jit_callable(func.args[0])
    return False


def _is_shard_map(func: ast.expr) -> bool:
    d = _dotted(func) or ""
    return d.split(".")[-1] == "shard_map"


def _jitted_function_defs(mod: Module) -> Dict[str, ast.FunctionDef]:
    """name -> FunctionDef for every function in the module that is
    decorated as, or wrapped into, a jit/shard_map region."""
    defs: Dict[str, ast.FunctionDef] = {}
    by_name: Dict[int, Dict[str, ast.FunctionDef]] = {}

    # collect all function defs per enclosing scope id so `jax.jit(f)`
    # can resolve `f` defined as a sibling (module level or closure)
    def collect(node, scope_key):
        local = by_name.setdefault(scope_key, {})
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local[child.name] = child
                collect(child, id(child))
            elif isinstance(child, ast.ClassDef):
                collect(child, id(child))
            else:
                collect(child, scope_key)

    collect(mod.tree, id(mod.tree))

    # decorated defs
    for scope in by_name.values():
        for name, fn in scope.items():
            for dec in fn.decorator_list:
                if _is_jit_callable(dec) or (
                    isinstance(dec, ast.Call)
                    and (_is_jit_callable(dec.func) or _is_shard_map(dec.func))
                ):
                    defs[name] = fn

    # wrapped references: jax.jit(f) / shard_map(f, ...) anywhere
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (_is_jit_callable(node.func) or _is_shard_map(node.func)):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                for scope in by_name.values():
                    fn = scope.get(arg.id)
                    if fn is not None:
                        defs[arg.id] = fn
    return defs


class _TaintChecker(ast.NodeVisitor):
    """Forward truthiness/taint pass over ONE jitted function body."""

    def __init__(self, mod: Module, fn: ast.FunctionDef,
                 findings: List[Finding]):
        self.mod = mod
        self.fn = fn
        self.findings = findings
        args = fn.args
        self.tainted: Set[str] = {
            a.arg for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ) if a.arg != "self"
        }

    # -- taint query -----------------------------------------------------------

    def _expr_tainted(self, expr: ast.expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                # prune: anything derived from .shape/.ndim/... is static.
                # ast.walk has no pruning, so mark the subtree's names.
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        sub._gk_static = True  # type: ignore[attr-defined]
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in ("len", "range", "enumerate"):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name):
                            sub._gk_static = True  # type: ignore[attr-defined]
        for node in ast.walk(expr):
            if (
                isinstance(node, ast.Name)
                and node.id in self.tainted
                and not getattr(node, "_gk_static", False)
            ):
                return True
        return False

    # -- statements ------------------------------------------------------------

    def visit_Assign(self, node: ast.Assign):
        tainted = self._expr_tainted(node.value)
        for tgt in node.targets:
            for name in ast.walk(tgt):
                if isinstance(name, ast.Name):
                    if tainted:
                        self.tainted.add(name.id)
                    else:
                        self.tainted.discard(name.id)
        # visit (not generic_visit): scalarizer/impure checks live in
        # visit_Call and must see the RHS call node itself
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign):
        if isinstance(node.target, ast.Name):
            if self._expr_tainted(node.value):
                self.tainted.add(node.target.id)
        self.visit(node.value)

    def _check_test(self, test: ast.expr, kind: str):
        if self._expr_tainted(test):
            self.findings.append(self.mod.finding(
                R_TRUTHY, test.lineno,
                f"{kind} on a traced value inside jitted "
                f"`{self.fn.name}` — use jnp.where/lax.cond; Python "
                "control flow concretizes the tracer",
            ))

    def visit_If(self, node: ast.If):
        self._check_test(node.test, "`if` truthiness")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_test(node.test, "`while` truthiness")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        self._check_test(node.test, "`assert` truthiness")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp):
        self._check_test(node.test, "conditional-expression truthiness")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        d = _dotted(node.func)
        if (
            d in _SCALARIZERS
            and node.args
            and self._expr_tainted(node.args[0])
        ):
            self.findings.append(self.mod.finding(
                R_TRUTHY, node.lineno,
                f"{d}() on a traced value inside jitted `{self.fn.name}` "
                "— scalar coercion concretizes the tracer",
            ))
        if d is not None:
            for prefix in _IMPURE_DOTTED:
                if d == prefix or d.startswith(prefix + "."):
                    self.findings.append(self.mod.finding(
                        R_IMPURE, node.lineno,
                        f"{d}() inside jitted `{self.fn.name}` executes "
                        "once at trace time and freezes into the "
                        "executable",
                    ))
                    break
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # nested defs trace separately
        return

    visit_AsyncFunctionDef = visit_FunctionDef


@register_pass
def trace_safety_pass(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        # cheap pre-filter: modules that never mention jit/shard_map
        # have no compiled regions to check
        if "jit" not in mod.source and "shard_map" not in mod.source:
            continue
        for name, fn in sorted(_jitted_function_defs(mod).items()):
            _TaintChecker(mod, fn, findings).visit(
                ast.Module(body=fn.body, type_ignores=[])
            )
        # jit-in-loop: a jit construction lexically inside for/while
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _is_jit_callable(sub.func):
                    findings.append(mod.finding(
                        R_JIT_LOOP, sub.lineno,
                        "jax.jit(...) constructed inside a loop — every "
                        "iteration re-hashes (or recompiles); hoist the "
                        "wrapper out of the loop",
                    ))
    return findings
