"""Lock-order and hold-while-blocking analysis (rules: lock-order-cycle,
blocking-under-lock, cv-held-lock).

Motivating incidents (docs/static-analysis.md has the full catalog):

* PR 6: the background delta-executable warm and a foreground sweep
  enqueued mesh collectives from different threads; the per-device launch
  orders interleaved and the AllReduce rendezvous deadlocked.  The fix
  (`parallel/mesh.py DISPATCH_LOCK`) is an ordering discipline — exactly
  the class of invariant a held-while-acquiring graph checks.
* PR 7: `MicroBatcher._adapt()` ran under the batcher condition variable
  while the service model took the driver lock; a long driver hold
  (audit sweep) stalled every enqueue behind the cv.

Model: every `with <lock-like>:` body and `<lock-like>.acquire()` call is
an acquisition site.  Lock-like expressions are recognized by name
(`*_lock`, `_mu`, `_cv`, `_cond`, `*gate`, `DISPATCH_LOCK`, ...) and
canonicalized to a project-wide identity — `self._lock` in class C of
module m is `m.C._lock`; module globals resolve through `from X import`
chains so `DISPATCH_LOCK` is one node everywhere.  Per function we record

  - ordered pairs (held -> acquired) from nested acquisitions,
  - calls made while holding each lock.

A name-based call graph (self-methods to the same class, bare names to
the same module, unique method names across the project) then propagates
each function's may-acquire and may-block sets, which yields:

  lock-order-cycle    an edge participating in a held-while-acquiring
                      cycle (the ABBA deadlock shape)
  blocking-under-lock an UNBOUNDED blocking call (socket/pipe reads,
                      subprocess waits, `time.sleep`, `join()`/`wait()`
                      without timeout) reachable while a lock is held
  cv-held-lock        acquiring another lock while holding a condition
                      variable (the PR 7 stall shape) — cv waits on the
                      cv itself are exempt (they release it)

Name-based resolution is deliberately conservative: unresolvable calls
contribute nothing, so every report points at a concrete chain.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Module, Project, register_pass, register_rule

R_CYCLE = register_rule(
    "lock-order-cycle",
    "locks are acquired in conflicting orders on different paths (ABBA "
    "deadlock shape)",
)
R_BLOCKING = register_rule(
    "blocking-under-lock",
    "an unbounded blocking call (pipe/socket read, subprocess wait, "
    "sleep, join()/wait() without timeout) runs while a lock is held",
)
R_CV_HELD = register_rule(
    "cv-held-lock",
    "another lock is acquired while a condition variable is held — a "
    "slow holder of the inner lock stalls every cv waiter (PR 7 shape)",
)

# terminal-name heuristic for lock-like attributes/globals
_LOCK_TERM = re.compile(r"(?:^|_)(lock|mu|cv|cond|gate)$", re.IGNORECASE)
# condition variables, for the cv-held-lock rule
_CV_TERM = re.compile(r"(?:^|_)(cv|cond)$", re.IGNORECASE)

# attribute calls that block unboundedly regardless of arguments
_BLOCKING_ATTRS = {
    "readline": "pipe/socket read",
    "readlines": "pipe/socket read",
    "recv": "socket read",
    "recvfrom": "socket read",
    "accept": "socket accept",
    "connect": "socket connect",
    "communicate": "subprocess wait",
    "check_output": "subprocess wait",
    "check_call": "subprocess wait",
    "urlopen": "HTTP round trip",
    "getresponse": "HTTP round trip",
    "block_until_ready": "device sync",
}
# modules whose .run/.call are subprocess entry points
_SUBPROCESS_BASES = {"subprocess", "_subprocess", "sp"}

# attribute-call names too ubiquitous for unique-name resolution: nearly
# every one shadows a stdlib method (Event.set, Queue.get, dict.update,
# Thread.start...), so "defined by exactly one class in the project"
# proves nothing about the receiver
_COMMON_METHODS = {
    "set", "get", "put", "clear", "pop", "append", "add", "remove",
    "discard", "update", "copy", "items", "keys", "values", "read",
    "write", "flush", "close", "open", "send", "start", "stop", "run",
    "join", "wait", "notify", "notify_all", "acquire", "release",
    "submit", "result", "cancel", "done", "next", "reset", "handle",
}

# the fault plane's sleep/hang IS the injected fault, not a real blocking
# call on the production path — its latency propagating through every
# `faults.fire()` call site would flag half the repo
_FAULT_MODULES = ("gatekeeper_tpu/faults/",)


def _dotted(expr: ast.expr) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_cv(lock_id: str) -> bool:
    return bool(_CV_TERM.search(lock_id.rsplit(".", 1)[-1]))


@dataclass
class _Call:
    held: Tuple[str, ...]
    target: Optional[str]  # resolution key, see _FnCollector._target
    line: int
    module: Module


@dataclass
class _Block:
    held: Tuple[str, ...]
    what: str
    line: int
    module: Module


@dataclass
class _FnSummary:
    qual: str  # modname::Class.method
    module: Module
    cls: Optional[str]
    name: str
    direct: Set[str] = field(default_factory=set)
    # (held, acquired, line) pairs from nested acquisition
    order: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[_Call] = field(default_factory=list)
    blocking: List[_Block] = field(default_factory=list)
    # blocking calls made with NO lock held — matter only transitively
    blocks_bare: List[Tuple[str, int]] = field(default_factory=list)


class _FnCollector(ast.NodeVisitor):
    """Single-function walker carrying the held-lock stack."""

    def __init__(self, summary: _FnSummary, module: Module):
        self.s = summary
        self.module = module
        self.held: List[str] = []

    # -- lock identity --------------------------------------------------------

    def _lock_id(self, expr: ast.expr) -> Optional[str]:
        dotted = _dotted(expr)
        if dotted is None:
            return None
        term = dotted.rsplit(".", 1)[-1]
        if not _LOCK_TERM.search(term):
            return None
        mod = self.module
        if "." not in dotted:  # module-global (or local) name
            origin = mod.import_origins.get(dotted)
            return origin if origin else f"{mod.modname}.{dotted}"
        base, rest = dotted.split(".", 1)
        if base == "self" and self.s.cls:
            return f"{mod.modname}.{self.s.cls}.{rest}"
        origin = mod.import_origins.get(base)
        if origin:
            return f"{origin}.{rest}"
        return f"{mod.modname}.{base}.{rest}"

    def _note_acquire(self, lock_id: str, line: int):
        for held in self.held:
            if held != lock_id:
                self.s.order.append((held, lock_id, line))
        self.s.direct.add(lock_id)

    # -- call classification ---------------------------------------------------

    def _target(self, func: ast.expr) -> Optional[str]:
        """Resolution key: 'self::name' | 'mod::name' | 'any::name'."""
        if isinstance(func, ast.Name):
            origin = self.module.import_origins.get(func.id)
            if origin:
                return f"import::{origin}"
            return f"mod::{self.module.modname}::{func.id}"
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                return (
                    f"self::{self.module.modname}::{self.s.cls}"
                    f"::{func.attr}"
                )
            return f"any::{func.attr}"
        return None

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        func = node.func
        kw = {k.arg for k in node.keywords}
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr in _BLOCKING_ATTRS:
                return _BLOCKING_ATTRS[attr]
            base = _dotted(func.value)
            if attr == "sleep" and base in ("time", "_time"):
                return "time.sleep"
            if attr in ("run", "call") and base in _SUBPROCESS_BASES:
                return "subprocess wait"
            if attr == "join" and not node.args and "timeout" not in kw:
                # zero-arg join is a thread join (str.join always takes
                # an argument); without timeout it waits forever
                return "join() without timeout"
            if attr == "wait" and not node.args and "timeout" not in kw:
                # Event/Condition/Popen wait without a bound.  Waiting on
                # a cv that is itself the (innermost) held lock releases
                # it — the canonical pattern — so only flag waits on
                # OTHER objects.
                rid = self._lock_id(func.value)
                if rid is None or rid not in self.held:
                    return "wait() without timeout"
            return None
        if isinstance(func, ast.Name):
            origin = self.module.import_origins.get(func.id, "")
            if func.id == "sleep" and origin == "time.sleep":
                return "time.sleep"
            if origin in ("urllib.request.urlopen",):
                return "HTTP round trip"
        return None

    # -- traversal -------------------------------------------------------------

    def visit_With(self, node: ast.With):
        acquired: List[str] = []
        for item in node.items:
            lid = self._lock_id(item.context_expr)
            if lid is not None:
                self._note_acquire(lid, node.lineno)
                self.held.append(lid)
                acquired.append(lid)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call):
        func = node.func
        # explicit .acquire() on a lock-like object counts as an
        # acquisition event for ordering (DispatchGate token style)
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            lid = self._lock_id(func.value)
            if lid is not None:
                self._note_acquire(lid, node.lineno)
        reason = self._blocking_reason(node)
        if reason is not None:
            if self.held and "blocking-under-lock" not in (
                self.module.suppressions.active_rules_for(node.lineno)
            ):
                self.s.blocking.append(_Block(
                    tuple(self.held), reason, node.lineno, self.module
                ))
            elif not self.held:
                self.s.blocks_bare.append((reason, node.lineno))
        target = self._target(func)
        if target is not None:
            self.s.calls.append(_Call(
                tuple(self.held), target, node.lineno, self.module
            ))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # nested defs analyzed separately
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # lambda bodies run later, not here
        return


def _collect_functions(project: Project) -> List[_FnSummary]:
    out: List[_FnSummary] = []
    for mod in project.modules:
        if mod.tree is None:
            continue

        def walk(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = (
                        f"{mod.modname}::{cls}.{child.name}"
                        if cls else f"{mod.modname}::{child.name}"
                    )
                    s = _FnSummary(qual, mod, cls, child.name)
                    coll = _FnCollector(s, mod)
                    for stmt in child.body:
                        coll.visit(stmt)
                    out.append(s)
                    # nested defs (closures, thread bodies) get their own
                    # summaries under the same class context
                    walk(child, cls)
                else:
                    walk(child, cls)

        walk(mod.tree, None)
    return out


class _Resolver:
    def __init__(self, fns: List[_FnSummary]):
        self.by_qual = {f.qual: f for f in fns}
        self.by_mod_name: Dict[Tuple[str, str], str] = {}
        self.by_cls_name: Dict[Tuple[str, str, str], str] = {}
        self.by_name: Dict[str, List[str]] = {}
        for f in fns:
            if f.cls is None:
                self.by_mod_name[(f.module.modname, f.name)] = f.qual
            else:
                self.by_cls_name[(f.module.modname, f.cls, f.name)] = f.qual
            self.by_name.setdefault(f.name, []).append(f.qual)

    def resolve(self, target: str) -> Optional[str]:
        kind, _, rest = target.partition("::")
        if kind == "self":
            modname, _, rest2 = rest.partition("::")
            cls, _, name = rest2.partition("::")
            return self.by_cls_name.get((modname, cls, name))
        if kind == "mod":
            modname, _, name = rest.partition("::")
            return self.by_mod_name.get((modname, name))
        if kind == "import":
            # 'pkg.mod.func' -> module-level function in an analyzed module
            if "." in rest:
                modpath, name = rest.rsplit(".", 1)
                for (m, n), qual in self.by_mod_name.items():
                    if n == name and (
                        m == modpath or m.endswith("/" + modpath)
                        or m.endswith("." + modpath) or modpath.endswith(m)
                    ):
                        return qual
            return None
        if kind == "any":
            # attribute call on an unknown object: resolve only when the
            # method name is defined by exactly ONE class in the project
            # AND does not shadow a ubiquitous stdlib method — anything
            # more aggressive invents call edges
            if rest in _COMMON_METHODS:
                return None
            quals = [
                q for q in self.by_name.get(rest, ())
                if self.by_qual[q].cls is not None
            ]
            if len(quals) == 1:
                return quals[0]
        return None


def _fixpoint(fns: List[_FnSummary], resolver: _Resolver):
    """Propagate may-acquire lock sets and may-block reasons through the
    call graph to a fixpoint."""
    may_acquire: Dict[str, Set[str]] = {f.qual: set(f.direct) for f in fns}

    def _injects_only(f: _FnSummary) -> bool:
        return any(
            f.module.relpath.startswith(p) for p in _FAULT_MODULES
        )

    may_block: Dict[str, Set[str]] = {
        f.qual: (
            set() if _injects_only(f)
            else {w for (w, _ln) in f.blocks_bare}
            | {b.what for b in f.blocking}
        )
        for f in fns
    }
    edges: Dict[str, Set[str]] = {}
    for f in fns:
        for c in f.calls:
            callee = resolver.resolve(c.target)
            if callee is not None and callee != f.qual:
                edges.setdefault(f.qual, set()).add(callee)
    for _ in range(30):  # deep chains converge far earlier
        changed = False
        for f in fns:
            for callee in edges.get(f.qual, ()):
                before = len(may_acquire[f.qual])
                may_acquire[f.qual] |= may_acquire[callee]
                if len(may_acquire[f.qual]) != before:
                    changed = True
                before = len(may_block[f.qual])
                may_block[f.qual] |= may_block[callee]
                if len(may_block[f.qual]) != before:
                    changed = True
        if not changed:
            break
    return may_acquire, may_block


@register_pass
def lock_pass(project: Project) -> List[Finding]:
    fns = _collect_functions(project)
    resolver = _Resolver(fns)
    may_acquire, may_block = _fixpoint(fns, resolver)

    # ---- edge set: direct nesting + call-propagated acquisitions ----------
    # edge -> (module, line, via) of one representative site
    edge_sites: Dict[Tuple[str, str], Tuple[Module, int, str]] = {}
    for f in fns:
        for held, acq, line in f.order:
            edge_sites.setdefault((held, acq), (f.module, line, ""))
        for c in f.calls:
            if not c.held:
                continue
            callee = resolver.resolve(c.target)
            if callee is None:
                continue
            for acq in may_acquire[callee]:
                for held in c.held:
                    if held != acq:
                        edge_sites.setdefault(
                            (held, acq),
                            (c.module, c.line, callee.split("::")[-1]),
                        )

    findings: List[Finding] = []

    # ---- lock-order cycles (Tarjan SCC over the lock digraph) -------------
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edge_sites:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan: the lock graph is small but recursion depth
        # must not depend on it
        work = [(v, iter(sorted(adj[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for scc in sccs:
        if len(scc) < 2:
            continue
        members = " <-> ".join(sorted(scc))
        for (a, b), (mod, line, via) in sorted(edge_sites.items()):
            if a in scc and b in scc:
                hop = f" (via {via}())" if via else ""
                findings.append(mod.finding(
                    R_CYCLE, line,
                    f"acquires {b} while holding {a}{hop}, but the "
                    f"opposite order also exists — deadlock cycle over "
                    f"{{{members}}}",
                ))

    # ---- cv-held-lock (the PR 7 stall shape) ------------------------------
    for (held, acq), (mod, line, via) in sorted(edge_sites.items()):
        if _is_cv(held) and not _is_cv(acq):
            hop = f" via {via}()" if via else ""
            findings.append(mod.finding(
                R_CV_HELD, line,
                f"acquires {acq}{hop} while holding condition variable "
                f"{held} — a slow holder of the inner lock stalls every "
                "cv waiter; restructure so the cv only guards queue "
                "state (see MicroBatcher._adapt, docs/static-analysis.md)",
            ))

    # ---- blocking-under-lock ----------------------------------------------
    for f in fns:
        for b in f.blocking:
            findings.append(b.module.finding(
                R_BLOCKING, b.line,
                f"{b.what} while holding {', '.join(b.held)} — bound it "
                "with a timeout or move it outside the critical section",
            ))
        for c in f.calls:
            if not c.held:
                continue
            callee = resolver.resolve(c.target)
            if callee is None:
                continue
            for what in sorted(may_block[callee]):
                findings.append(c.module.finding(
                    R_BLOCKING, c.line,
                    f"call to {callee.split('::')[-1]}() may perform "
                    f"{what} while holding {', '.join(c.held)}",
                ))
    return findings
