"""gklint core: project model, findings, suppressions, baseline.

The analyzer is deliberately import-free with respect to the code it
checks: every pass works on `ast` trees plus raw source text, so linting
the repo never initializes JAX, binds ports, or spawns threads.  A
"project" is the set of parsed modules under the paths handed to the CLI;
passes are either per-module (most rules) or whole-project (the lock-order
graph, the registry cross-checks).

Suppression contract (docs/static-analysis.md):

    x = risky()  # gklint: disable=rule-name -- why this is safe

A disable comment applies to findings on its own line, or — when the
comment stands alone — to the next source line (chains of comment lines
stack).  The ``-- reason`` is REQUIRED: a disable without one is itself a
finding (``suppression-reason``), so every suppression in the tree
carries its justification next to the code it excuses.

File-level escape hatch for generated/fixture files:

    # gklint: disable-file=rule-name -- reason

The committed baseline (.gklint-baseline.json at the repo root) absorbs
residual findings by (rule, path, enclosing-scope) key so the tree runs
clean at zero UNSUPPRESSED findings; `--write-baseline` regenerates it.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

# ---- findings ---------------------------------------------------------------

#: rule-id -> one-line description, populated by register_rule()
RULES: Dict[str, str] = {}


def register_rule(rule: str, doc: str) -> str:
    RULES[rule] = doc
    return rule


R_SUPPRESSION = register_rule(
    "suppression-reason",
    "a `# gklint: disable=` comment must carry a `-- reason`",
)
R_UNKNOWN_RULE = register_rule(
    "unknown-rule",
    "a `# gklint: disable=` comment names a rule that does not exist",
)


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    context: str = ""  # enclosing class.function qualname, "" at module level

    def key(self) -> tuple:
        # line numbers are deliberately NOT part of the identity: a
        # baseline must survive unrelated edits shifting code downward
        return (self.rule, self.path, self.context)

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{where}: {self.rule}: {self.message}{ctx}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "context": self.context,
            "message": self.message,
        }


# ---- suppressions -----------------------------------------------------------

_DISABLE_RE = re.compile(
    r"gklint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+?)"
    r"\s*(?:--\s*(\S.*))?$"
)


@dataclass
class Suppression:
    rules: Set[str]
    reason: Optional[str]
    line: int
    standalone: bool  # comment is the only thing on its line


class SuppressionSet:
    """Per-file disable comments, resolved from the token stream (never
    from regexing raw lines — '#' inside string literals must not count)."""

    def __init__(self):
        self.by_line: Dict[int, Suppression] = {}
        self.file_rules: Set[str] = set()
        self.problems: List[tuple] = []  # (line, rule, message)
        # lines that are standalone comments (suppression or not): a
        # disable at the top of a multi-line comment block still covers
        # the statement below the block
        self.comment_lines: Set[int] = set()

    @classmethod
    def collect(cls, source: str) -> "SuppressionSet":
        out = cls()
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return out
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            if tok.line.strip().startswith("#"):
                out.comment_lines.add(tok.start[0])
            m = _DISABLE_RE.search(tok.string)
            if m is None:
                if "gklint:" in tok.string:
                    out.problems.append((
                        tok.start[0], R_SUPPRESSION,
                        "unparseable gklint comment "
                        "(want `# gklint: disable=<rule> -- <reason>`)",
                    ))
                continue
            kind, rules_s, reason = m.group(1), m.group(2), m.group(3)
            rules = {r.strip() for r in rules_s.split(",") if r.strip()}
            line = tok.start[0]
            for r in rules:
                if r not in RULES:
                    out.problems.append((
                        line, R_UNKNOWN_RULE,
                        f"disable names unknown rule {r!r} "
                        f"(see `gklint --list-rules`)",
                    ))
            if not reason:
                out.problems.append((
                    line, R_SUPPRESSION,
                    "suppression without a reason — append `-- <why>`",
                ))
                # an unreasoned disable still suppresses: the finding about
                # the missing reason is the enforcement, and double-reporting
                # the original would punish the annotated line twice
            standalone = tok.line.strip().startswith("#")
            if kind == "disable-file":
                out.file_rules |= rules
            else:
                prev = out.by_line.get(line)
                if prev is not None:
                    prev.rules |= rules
                else:
                    out.by_line[line] = Suppression(
                        rules, reason, line, standalone
                    )
        return out

    def active_rules_for(self, line: int) -> Set[str]:
        """Rules suppressed at `line`: file-level ones, a same-line
        disable, or a standalone-comment chain immediately above."""
        rules = set(self.file_rules)
        sup = self.by_line.get(line)
        if sup is not None:
            rules |= sup.rules
        probe = line - 1
        while probe > 0 and probe in self.comment_lines:
            sup = self.by_line.get(probe)
            if sup is not None and sup.standalone:
                rules |= sup.rules
            probe -= 1
        return rules

    def suppressed(self, finding: Finding) -> bool:
        return finding.rule in self.active_rules_for(finding.line)


# ---- module / project model -------------------------------------------------


class Module:
    """One parsed source file plus derived lookup structures."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[str] = None
        try:
            self.tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            self.syntax_error = f"{e.msg} (line {e.lineno})"
        self.suppressions = SuppressionSet.collect(source)
        # name -> "pkg.mod.name" for `from X import name` (relative dots
        # collapsed); used to canonicalize shared locks like DISPATCH_LOCK
        self.import_origins: Dict[str, str] = {}
        # enclosing-scope map: (lineno -> qualname) resolved lazily
        self._scopes: Optional[List[tuple]] = None
        if self.tree is not None:
            self._collect_imports()

    # a stable module handle: path without .py, slashes -> dots
    @property
    def modname(self) -> str:
        rel = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
        return rel.replace("/", ".")

    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.import_origins[local] = (
                        f"{node.module}.{alias.name}"
                    )

    def scope_at(self, line: int) -> str:
        """Qualname of the innermost class/function containing `line`."""
        if self._scopes is None:
            spans: List[tuple] = []

            def visit(node, prefix):
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                    ):
                        qual = f"{prefix}{child.name}"
                        end = getattr(child, "end_lineno", child.lineno)
                        spans.append((child.lineno, end, qual))
                        visit(child, qual + ".")
                    else:
                        visit(child, prefix)

            if self.tree is not None:
                visit(self.tree, "")
            spans.sort(key=lambda s: (s[0], -s[1]))
            self._scopes = spans
        best = ""
        for lo, hi, qual in self._scopes:
            if lo <= line <= hi:
                best = qual  # spans sorted outer-first; keep innermost
        return best

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = (
            node_or_line
            if isinstance(node_or_line, int)
            else getattr(node_or_line, "lineno", 1)
        )
        return Finding(rule, self.relpath, line, message, self.scope_at(line))


class Project:
    """The analyzed file set.  `root` anchors repo-relative paths and the
    doc/registry cross-checks (docs/, faults/, catalog live under it)."""

    def __init__(self, root: str, modules: List[Module]):
        self.root = root
        self.modules = modules

    @classmethod
    def load(cls, root: str, paths: Sequence[str],
             exclude: Sequence[str] = ()) -> "Project":
        root = os.path.abspath(root)
        files: List[str] = []
        for p in paths:
            p = os.path.abspath(p)
            if os.path.isfile(p) and p.endswith(".py"):
                files.append(p)
            elif os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if d != "__pycache__" and not d.startswith(".")
                    )
                    for f in sorted(filenames):
                        if f.endswith(".py"):
                            files.append(os.path.join(dirpath, f))
        seen = set()
        modules = []
        for f in files:
            if f in seen:
                continue
            seen.add(f)
            rel = os.path.relpath(f, root).replace(os.sep, "/")
            if any(rel.startswith(e) for e in exclude):
                continue
            try:
                with open(f, encoding="utf-8") as fh:
                    source = fh.read()
            except (OSError, UnicodeDecodeError):
                continue
            modules.append(Module(f, rel, source))
        return cls(root, modules)


# ---- pass registry ----------------------------------------------------------

#: callables Project -> Iterable[Finding]
PASSES: List[Callable[[Project], Iterable[Finding]]] = []


def register_pass(fn):
    PASSES.append(fn)
    return fn


def _suppression_findings(project: Project) -> List[Finding]:
    out = []
    for mod in project.modules:
        for line, rule, msg in mod.suppressions.problems:
            out.append(Finding(rule, mod.relpath, line, msg,
                               mod.scope_at(line)))
        if mod.syntax_error is not None:
            out.append(Finding(
                "unknown-rule", mod.relpath, 1,
                f"file does not parse: {mod.syntax_error}", "",
            ))
    return out


def run_passes(project: Project,
               select: Optional[Set[str]] = None) -> List[Finding]:
    """All raw findings (suppressions applied, baseline NOT applied)."""
    raw: List[Finding] = list(_suppression_findings(project))
    for p in PASSES:
        raw.extend(p(project))
    by_path = {m.relpath: m for m in project.modules}
    out = []
    for f in raw:
        if select is not None and f.rule not in select:
            continue
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressions.suppressed(f):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# ---- baseline ---------------------------------------------------------------

BASELINE_NAME = ".gklint-baseline.json"


def load_baseline(path: str) -> Dict[tuple, int]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    counts: Dict[tuple, int] = {}
    for entry in data.get("findings", []):
        key = (entry.get("rule", ""), entry.get("path", ""),
               entry.get("context", ""))
        counts[key] = counts.get(key, 0) + int(entry.get("count", 1))
    return counts


def write_baseline(path: str, findings: List[Finding]) -> None:
    counts: Dict[tuple, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": rule, "path": p, "context": ctx, "count": n}
        for (rule, p, ctx), n in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": (
            "gklint baseline: accepted findings by (rule, path, context). "
            "Regenerate with `python tools/gklint.py --write-baseline`; "
            "prefer fixing or inline `# gklint: disable=... -- reason`."
        ), "findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: List[Finding],
                   baseline: Dict[tuple, int]) -> List[Finding]:
    budget = dict(baseline)
    out = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            continue
        out.append(f)
    return out
