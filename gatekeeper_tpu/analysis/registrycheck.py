"""Cross-registry conformance (rules: unknown-fault-point,
undocumented-fault-point, undocumented-metric).

The `tools/check_observability.py` discipline, folded into gklint and
extended to the fault plane — purely static (AST + text), so linting
never imports the modules under check:

unknown-fault-point       every `faults.fire(<point>)` site must use a
                          constant defined in `faults/__init__.py` and
                          listed in ALL_POINTS; a raw string literal (or
                          an unlisted constant) is an unregistered point
                          chaos specs cannot target.
undocumented-fault-point  every ALL_POINTS entry appears in
                          docs/failure-modes.md (the operator contract
                          for chaos drills).
undocumented-metric       every `View("name", ...)` in
                          metrics/catalog.py appears in docs/metrics.md.

These project-level checks only run when the analyzed file set actually
contains the registries (linting a fixture directory skips them).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from .core import Finding, Module, Project, register_pass, register_rule

R_UNKNOWN_POINT = register_rule(
    "unknown-fault-point",
    "faults.fire() with a point that is not a registered ALL_POINTS "
    "constant",
)
R_UNDOC_POINT = register_rule(
    "undocumented-fault-point",
    "a fault point in faults.ALL_POINTS is missing from "
    "docs/failure-modes.md",
)
R_UNDOC_METRIC = register_rule(
    "undocumented-metric",
    "a metric view in metrics/catalog.py is missing from docs/metrics.md",
)

_FAULTS_MOD = "gatekeeper_tpu/faults/__init__.py"
_CATALOG_MOD = "gatekeeper_tpu/metrics/catalog.py"


def _find(project: Project, relpath: str) -> Optional[Module]:
    for mod in project.modules:
        if mod.relpath == relpath:
            return mod
    return None


def _read_doc(project: Project, rel: str) -> Optional[str]:
    path = os.path.join(project.root, rel)
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _fault_registry(mod: Module):
    """(constant name -> point string, set of ALL_POINTS constant names)"""
    consts: Dict[str, str] = {}
    listed: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                consts[tgt.id] = node.value.value
            elif tgt.id == "ALL_POINTS" and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Name):
                        listed.add(elt.id)
    return consts, listed


@register_pass
def registry_pass(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    faults_mod = _find(project, _FAULTS_MOD)
    if faults_mod is not None and faults_mod.tree is not None:
        consts, listed = _fault_registry(faults_mod)
        point_values = {consts[c] for c in listed if c in consts}

        # every fire() site uses a registered constant
        for mod in project.modules:
            if mod.tree is None or ".fire(" not in mod.source:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not (
                    isinstance(func, ast.Attribute) and func.attr == "fire"
                ):
                    continue
                base = func.value
                base_name = getattr(base, "id", getattr(base, "attr", ""))
                if "faults" not in str(base_name):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    if arg.value not in point_values:
                        findings.append(mod.finding(
                            R_UNKNOWN_POINT, node.lineno,
                            f"faults.fire({arg.value!r}) uses a string "
                            "literal not registered in faults.ALL_POINTS "
                            "— add a named constant so chaos specs can "
                            "target it",
                        ))
                elif isinstance(arg, ast.Attribute):
                    if arg.attr not in listed:
                        findings.append(mod.finding(
                            R_UNKNOWN_POINT, node.lineno,
                            f"faults.fire(faults.{arg.attr}) — "
                            f"{arg.attr} is not listed in "
                            "faults.ALL_POINTS",
                        ))

        # every registered point is documented
        doc = _read_doc(project, "docs/failure-modes.md")
        if doc is not None:
            for cname in sorted(listed):
                value = consts.get(cname)
                if value is not None and value not in doc:
                    findings.append(faults_mod.finding(
                        R_UNDOC_POINT, 1,
                        f"fault point {value!r} ({cname}) is not "
                        "documented in docs/failure-modes.md",
                    ))

    catalog_mod = _find(project, _CATALOG_MOD)
    if catalog_mod is not None and catalog_mod.tree is not None:
        doc = _read_doc(project, "docs/metrics.md")
        if doc is not None:
            for node in ast.walk(catalog_mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = getattr(node.func, "id",
                                getattr(node.func, "attr", ""))
                if fname != "View" or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    if arg.value not in doc:
                        findings.append(catalog_mod.finding(
                            R_UNDOC_METRIC, node.lineno,
                            f"metric view {arg.value!r} is not "
                            "documented in docs/metrics.md",
                        ))
    return findings
