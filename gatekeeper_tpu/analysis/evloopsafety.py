"""Event-loop socket discipline (rule: blocking-socket-in-loop) —
ISSUE 19.

A selectors-based reactor serves every connection from ONE thread: a
single blocking socket call on that thread parks the whole edge — every
pipelined client, every wire backend, the timer wheel — behind one slow
peer.  That is the exact failure the event-loop rewrite exists to
remove, and it regresses silently: the code still works on a warm
loopback bench and collapses under the first stalled peer in
production.

This pass machine-checks the discipline inside event-loop modules (any
module that imports ``selectors``):

blocking-socket-in-loop
    (1) ``.sendall(...)`` / ``.makefile(...)`` anywhere in the module —
    ``sendall`` spins/blocks until the kernel drains the buffer (the
    reactor must buffer and wait for EVENT_WRITE instead), and
    ``makefile`` wraps the socket in blocking file I/O.
    (2) ``.recv/.recv_into/.accept/.send/.connect(...)`` on a receiver
    with no non-blocking evidence in the module: the receiver's
    terminal name (leading underscores stripped, so ``self._lsock``
    matches ``lsock``) never received ``.setblocking(False)`` and never
    appears as the first argument to a ``*.register(...)`` /
    ``*.modify(...)`` selector call.  ``connect_ex`` is the sanctioned
    non-blocking connect and is not flagged.

Name-based evidence is deliberately coarse but errs quiet: any
``setblocking(False)`` or selector registration of the same terminal
name anywhere in the module clears that name.  Genuine off-loop helpers
inside an event-loop module (a probe thread, a test shim) carry
reasoned ``# gklint: disable=blocking-socket-in-loop`` suppressions —
which is exactly the "this runs off-loop because..." documentation the
next reader needs.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, Project, register_pass, register_rule

R_BLOCKING_SOCKET = register_rule(
    "blocking-socket-in-loop",
    "a blocking socket call inside an event-loop module — one stalled "
    "peer parks the whole reactor; use the non-blocking Conn/selector "
    "machinery (or justify an off-loop helper with a suppression)",
)

# always wrong in an event-loop module, no receiver analysis needed
_ALWAYS = {
    "sendall": "blocks until the kernel drains the send buffer — "
               "buffer the bytes and wait for EVENT_WRITE",
    "makefile": "wraps the socket in blocking file I/O",
}

# blocking unless the receiver has non-blocking evidence
_GUARDED = ("recv", "recv_into", "recvfrom", "accept", "send", "connect")


def _terminal(expr: ast.expr) -> Optional[str]:
    """Normalized terminal name of a Name/Attribute receiver chain:
    ``self._lsock`` -> ``lsock``, ``sock`` -> ``sock``."""
    if isinstance(expr, ast.Attribute):
        return expr.attr.lstrip("_") or None
    if isinstance(expr, ast.Name):
        return expr.id.lstrip("_") or None
    return None


def _imports_selectors(mod) -> bool:
    if mod.tree is None:
        return False
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "selectors"
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "selectors":
                return True
    return False


def _nonblocking_names(tree: ast.AST) -> Set[str]:
    """Terminal receiver names with non-blocking evidence: given
    ``.setblocking(False)``, or registered with a selector via
    ``*.register(x, ...)`` / ``*.modify(x, ...)``."""
    safe: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr == "setblocking" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and arg.value is False:
                name = _terminal(fn.value)
                if name:
                    safe.add(name)
        elif fn.attr in ("register", "modify") and node.args:
            name = _terminal(node.args[0])
            if name:
                safe.add(name)
    return safe


@register_pass
def evloopsafety_pass(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None or not _imports_selectors(mod):
            continue
        safe = _nonblocking_names(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr in _ALWAYS:
                findings.append(mod.finding(
                    R_BLOCKING_SOCKET, node.lineno,
                    f".{fn.attr}() in an event-loop module: "
                    f"{_ALWAYS[fn.attr]}",
                ))
                continue
            if fn.attr not in _GUARDED:
                continue
            name = _terminal(fn.value)
            if name is not None and name in safe:
                continue
            findings.append(mod.finding(
                R_BLOCKING_SOCKET, node.lineno,
                f".{fn.attr}() on {name or 'an expression'!s} with no "
                "setblocking(False)/selector registration in this "
                "module — a blocking call here parks the reactor",
            ))
    return findings
