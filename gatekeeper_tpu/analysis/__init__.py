"""gklint — repo-invariant static analysis for gatekeeper_tpu.

The concurrency and tracing invariants this data plane depends on were
each learned the hard way (the PR 6 mesh AllReduce rendezvous deadlock,
the PR 7 cv-held-driver-lock stall, wedged-pipe reader hangs); gklint
machine-checks them on every run instead of rediscovering them in review.

CLI: ``python tools/gklint.py [paths...]``; wired into tier-1 via
tests/test_gklint_tool.py.  Rule catalog + incident history:
docs/static-analysis.md.

Pass families (each module registers its rules on import):

  locks          lock-order cycles, blocking calls under locks, locks
                 acquired under condition variables
  tracesafety    tracer truthiness / jit-in-loop / impure calls in
                 compiled regions
  failpolicy     silently swallowed exceptions on admission/audit paths
  hygiene        thread daemon/join, bare joins, listener close,
                 idempotent start()
  queuebound     unbounded queues (queue.Queue() without maxsize,
                 list-backed pending queues on serving paths)
  evloopsafety   blocking socket calls inside selectors-based
                 event-loop modules (ISSUE 19 reactor discipline)
  registrycheck  fault-point and metric registries vs their docs
"""

from .core import (  # noqa: F401
    BASELINE_NAME,
    PASSES,
    RULES,
    Finding,
    Module,
    Project,
    apply_baseline,
    load_baseline,
    run_passes,
    write_baseline,
)

# importing the pass modules registers them with core.PASSES
from . import evloopsafety  # noqa: F401,E402
from . import failpolicy  # noqa: F401,E402
from . import hygiene  # noqa: F401,E402
from . import locks  # noqa: F401,E402
from . import queuebound  # noqa: F401,E402
from . import registrycheck  # noqa: F401,E402
from . import tracesafety  # noqa: F401,E402


def lint(root: str, paths, exclude=(), select=None):
    """Parse `paths` (files/dirs) under repo `root` and run every pass.
    Returns the suppression-filtered findings (baseline NOT applied)."""
    project = Project.load(root, paths, exclude=exclude)
    return run_passes(project, select=select)
