"""Fail-open/closed audit (rule: swallowed-exception).

PR 1 made admission failure an EXPLICIT decision: when the deadline
budget is exhausted or a backend fails, `ValidationHandler` routes
through the configured fail-open/fail-closed policy and records the
outcome.  A `except Exception: pass` on those paths silently converts a
backend failure into... nothing — on the admission path that's an
implicit fail-open nobody chose; on the audit path it's a sweep that
"succeeded" with missing violations.

The rule: an exception handler that catches broadly (bare `except:`,
`except Exception`, `except BaseException`) and whose body does NOTHING
— only `pass`/`...`/`continue` — is flagged.  Handlers that log, record
a metric, set state, return a value, or re-raise are fine: the point is
that SOMETHING observable must happen.  On modules outside the
admission/audit path the rule still applies (a silent swallow is never
load-bearing), but the message names the policy routing only for path
modules.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, Project, register_pass, register_rule

R_SWALLOW = register_rule(
    "swallowed-exception",
    "a broad except handler silently swallows (body is only pass/"
    "continue) — route through the explicit fail-open/closed decision "
    "or at least log",
)

# repo-relative prefixes where a swallow is an admission/audit policy bug
_PATH_PREFIXES = (
    "gatekeeper_tpu/webhook/", "gatekeeper_tpu/audit/",
    "gatekeeper_tpu/deadline.py", "gatekeeper_tpu/ops/driver.py",
    "gatekeeper_tpu/fleet/",
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", ""))]
    return any(n in _BROAD for n in names)


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / ellipsis
        return False
    return True


@register_pass
def fail_policy_pass(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for mod in project.modules:
        if mod.tree is None:
            continue
        on_path = any(mod.relpath.startswith(p) for p in _PATH_PREFIXES)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not (_is_broad(node) and _is_silent(node)):
                continue
            if on_path:
                msg = (
                    "broad except silently swallows on an admission/audit "
                    "path — failures here must route through the explicit "
                    "deadline fail-open/closed decision (deadline.py, "
                    "docs/failure-modes.md), or at least log and count"
                )
            else:
                msg = (
                    "broad except with an empty body silently swallows — "
                    "log, count, or narrow the exception type"
                )
            findings.append(mod.finding(R_SWALLOW, node.lineno, msg))
    return findings
