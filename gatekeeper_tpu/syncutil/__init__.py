"""Synchronization utilities (reference pkg/syncutil/).

- SingleRunner: keyed singleton workers with per-key cancel
  (single_runner.go:28-44); keys are single-use, duplicate scheduling is
  silently ignored
- SyncBool: lock-guarded boolean (syncbool.go)
- backoff: capped exponential backoff with jitter (backoff.go /
  wait.ExponentialBackoff usage across audit/upgrade loops)
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional


class SyncBool:
    def __init__(self, value: bool = False):
        self._lock = threading.Lock()
        self._value = value

    def get(self) -> bool:
        with self._lock:
            return self._value

    def set(self, value: bool):
        with self._lock:
            self._value = value


class Backoff:
    """Capped exponential backoff with deterministic-seedable jitter.

    The nominal schedule is base * factor^k hard-capped at `cap`; each
    interval is then jittered DOWNWARD into [nominal * (1 - jitter),
    nominal], so the cap stays a hard upper bound while a fleet of
    reconnecting watchers desynchronizes instead of storming the API
    server in lockstep.  Pass a seeded `random.Random` for reproducible
    schedules (the chaos suite does)."""

    def __init__(
        self,
        base: float = 0.05,
        factor: float = 2.0,
        cap: float = 2.0,
        jitter: float = 0.5,
        rng: Optional[random.Random] = None,
    ):
        if base <= 0 or factor < 1.0 or cap < base:
            raise ValueError("backoff requires base > 0, factor >= 1, "
                             "cap >= base")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.base = base
        self.factor = factor
        self.cap = cap
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._cur = base

    def next(self) -> float:
        """The next sleep interval; advances the schedule."""
        nominal = min(self._cur, self.cap)
        self._cur = min(self._cur * self.factor, self.cap)
        if self.jitter:
            nominal -= nominal * self.jitter * self._rng.random()
        return nominal

    def reset(self):
        """Back to the base interval (call after a successful attempt)."""
        self._cur = self.base


def backoff_intervals(
    initial: float = 1.0,
    factor: float = 2.0,
    steps: int = 5,
    jitter: float = 0.0,
) -> Iterator[float]:
    """The wait.Backoff{Duration,Factor,Jitter,Steps} shape the reference
    uses for its retry loops (audit manager.go:693-700)."""
    d = initial
    for _ in range(steps):
        if jitter > 0:
            yield d + random.uniform(0, d * jitter)
        else:
            yield d
        d *= factor


def retry_with_backoff(
    fn: Callable[[], bool],
    initial: float = 0.05,
    factor: float = 2.0,
    steps: int = 5,
) -> bool:
    """Run fn until it returns True (done) or steps are exhausted."""
    if fn():
        return True
    for interval in backoff_intervals(initial, factor, steps - 1):
        time.sleep(interval)
        if fn():
            return True
    return False


class SingleRunner:
    """Keyed singleton worker threads.  Each key schedules at most once for
    the runner's lifetime; cancel(key) signals that worker's stop event.
    Workers receive the stop event and must respect it, as goroutines
    respect their context in the reference."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cancels: Dict[str, threading.Event] = {}
        self._threads: List[threading.Thread] = []
        self._done = False

    def schedule(self, key: str, fn: Callable[[threading.Event], None]) -> bool:
        """Start fn(stop_event) under key; returns False if the key was
        already used (silently ignored, single_runner.go:28-44) or the
        runner is shut down."""
        with self._lock:
            if self._done or key in self._cancels:
                return False
            stop = threading.Event()
            self._cancels[key] = stop
            t = threading.Thread(
                target=fn, args=(stop,), name=f"single-{key}", daemon=True
            )
            self._threads.append(t)
            t.start()
            return True

    def cancel(self, key: str):
        with self._lock:
            ev = self._cancels.get(key)
        if ev is not None:
            ev.set()

    def wait(self, timeout: Optional[float] = None):
        """Cancel everything and join all workers."""
        with self._lock:
            self._done = True
            events = list(self._cancels.values())
            threads = list(self._threads)
        for ev in events:
            ev.set()
        deadline = (time.monotonic() + timeout) if timeout else None
        for t in threads:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            t.join(timeout=remaining)
