"""Readiness tracker (reference pkg/readiness/): the startup gate.

Before a pod reports ready it must have ingested every pre-existing
ConstraintTemplate, every constraint of every template's kind, the Config
singleton, and every to-be-synced data object — otherwise the webhook could
serve decisions from a partially-rebuilt engine.  Controllers call
`tracker.for_gvk(...).observe(obj)` as they ingest; `run()` seeds the
expectations by listing current state (ready_tracker.go:176-225).

Satisfaction circuit-breaks: once a tracker is satisfied it stays satisfied
and drops its bookkeeping (ready_tracker.go:137-172, object_tracker.go).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Set, Tuple

from ..apis.config import CONFIG_NAME
from ..apis.config import GVK as CONFIG_GVK
from ..apis.config import parse_config
from ..kube.inmem import InMemoryKube, obj_key as _key
from ..util import nested_get

GVK = Tuple[str, str, str]

TEMPLATES_GVK = ("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
CONSTRAINTS_GROUP = "constraints.gatekeeper.sh"

# TryCancelExpect cancels only after this many attempts for the same object
# (object_tracker.go tryCancelled semantics)
TRY_CANCEL_THRESHOLD = 3


def template_constraint_kind(template: dict) -> Optional[str]:
    return nested_get(template, "spec", "crd", "spec", "names", "kind")


class ObjectTracker:
    """Expectations for one GVK (object_tracker.go:33-62)."""

    def __init__(self, gvk: GVK):
        self.gvk = gvk
        self._lock = threading.RLock()
        self._expect: Set[Tuple[str, str]] = set()
        self._seen: Set[Tuple[str, str]] = set()
        self._canceled: Set[Tuple[str, str]] = set()
        self._try_cancels: Dict[Tuple[str, str], int] = {}
        self._populated = False
        self._satisfied = False  # circuit breaker

    def expect(self, obj: dict):
        with self._lock:
            if self._satisfied:
                return
            self._expect.add(_key(obj))

    def observe(self, obj: dict):
        with self._lock:
            if self._satisfied:
                return
            self._seen.add(_key(obj))

    def cancel_expect(self, obj: dict):
        """Deleted-but-expected objects stop blocking readiness
        (object_tracker.go CancelExpect)."""
        with self._lock:
            if self._satisfied:
                return
            self._canceled.add(_key(obj))

    def try_cancel_expect(self, obj: dict) -> bool:
        """Soft cancel: only takes effect after TRY_CANCEL_THRESHOLD calls
        for the same object — guards against transient NotFound races."""
        with self._lock:
            if self._satisfied:
                return True
            k = _key(obj)
            n = self._try_cancels.get(k, 0) + 1
            self._try_cancels[k] = n
            if n >= TRY_CANCEL_THRESHOLD:
                self._canceled.add(k)
                return True
            return False

    def expectations_done(self):
        """No further Expect calls will arrive (population finished)."""
        with self._lock:
            self._populated = True

    @property
    def populated(self) -> bool:
        with self._lock:
            return self._populated

    def satisfied(self) -> bool:
        with self._lock:
            if self._satisfied:
                return True
            if not self._populated:
                return False
            if self._expect <= (self._seen | self._canceled):
                # circuit break: free the bookkeeping
                self._satisfied = True
                self._expect.clear()
                self._seen.clear()
                self._canceled.clear()
                self._try_cancels.clear()
                return True
            return False

    def cancel_all(self):
        """Stop tracking this kind entirely (its source object is gone):
        short-circuit to satisfied."""
        with self._lock:
            self._populated = True
            self._satisfied = True
            self._expect.clear()
            self._seen.clear()
            self._canceled.clear()
            self._try_cancels.clear()

    def pending(self) -> Set[Tuple[str, str]]:
        with self._lock:
            if self._satisfied:
                return set()
            return self._expect - self._seen - self._canceled


class Tracker:
    """ready_tracker.go: the aggregate gate over templates, per-kind
    constraints, config, and synced data."""

    def __init__(self):
        self._lock = threading.RLock()
        self.templates = ObjectTracker(TEMPLATES_GVK)
        self.config = ObjectTracker(CONFIG_GVK)
        self._constraints: Dict[GVK, ObjectTracker] = {}
        self._data: Dict[GVK, ObjectTracker] = {}
        self._constraints_populated = False
        self._data_populated = False
        self._satisfied = False
        self._seeded = False  # run() finished; late trackers are born populated

    # ---- tracker access (ready_tracker.go For/ForData) -------------------

    def for_gvk(self, gvk: GVK) -> ObjectTracker:
        if gvk == TEMPLATES_GVK:
            return self.templates
        if gvk == CONFIG_GVK:
            return self.config
        with self._lock:
            tr = self._constraints.get(gvk)
            if tr is None:
                tr = self._constraints[gvk] = ObjectTracker(gvk)
                if self._seeded:
                    # kinds appearing after seeding carry no startup debt
                    tr.expectations_done()
            return tr

    def for_data(self, gvk: GVK) -> ObjectTracker:
        with self._lock:
            tr = self._data.get(gvk)
            if tr is None:
                tr = self._data[gvk] = ObjectTracker(gvk)
                if self._seeded:
                    tr.expectations_done()
            return tr

    def cancel_template(self, template: dict):
        """Template deleted (or failed compile) during startup: cancel it AND
        its constraint kind's expectations — those constraints can never be
        observed once the kind's watch is gone (collectForObjectTracker,
        ready_tracker.go:228-260)."""
        self.templates.cancel_expect(template)
        kind = template_constraint_kind(template)
        if kind:
            with self._lock:
                tr = self._constraints.get((CONSTRAINTS_GROUP, "v1beta1", kind))
            if tr is not None:
                tr.cancel_all()

    # ---- seeding ----------------------------------------------------------

    def run(self, kube: InMemoryKube):
        """Seed expectations from current cluster state
        (ready_tracker.go:176-225).  Templates and config are listed here;
        constraints per kind are expected from each template's listed CRs;
        data expectations come from the Config sync set."""
        templates = kube.list(TEMPLATES_GVK)
        for t in templates:
            self.templates.expect(t)
        self.templates.expectations_done()

        # constraints: for each template kind, expect existing CRs
        for t in templates:
            kind = template_constraint_kind(t)
            if not kind:
                continue
            cgvk = (CONSTRAINTS_GROUP, "v1beta1", kind)
            tr = self.for_gvk(cgvk)
            for c in kube.list(cgvk):
                tr.expect(c)
            tr.expectations_done()
        with self._lock:
            self._constraints_populated = True

        # config + data sync set
        cfg = None
        try:
            cfg = kube.get(CONFIG_GVK, CONFIG_NAME, "gatekeeper-system")
        except Exception:
            # only the singleton name is honored — a config with any other
            # name is ignored by the config controller, so expecting it
            # would deadlock readiness (ready_tracker.go skips them)
            for c in kube.list(CONFIG_GVK):
                if _key(c)[1] == CONFIG_NAME:
                    cfg = c
                    break
        if cfg is not None:
            self.config.expect(cfg)
            spec = parse_config(cfg)
            for entry in spec.sync_only:
                gvk = entry.gvk()
                tr = self.for_data(gvk)
                for obj in kube.list(gvk):
                    tr.expect(obj)
                tr.expectations_done()
        self.config.expectations_done()
        with self._lock:
            self._data_populated = True
            self._seeded = True

    def collect(self, kube: InMemoryKube):
        """Cancel expectations for objects that no longer exist — the
        periodic deleted-object collection of ready_tracker.go:198-218 /
        collectForObjectTracker:228-260.  Covers objects deleted in the
        window between run() seeding and watch registration, when no
        DELETED tombstone is ever delivered."""

        def _collect(tr: ObjectTracker, gvk: GVK):
            pending = tr.pending()
            if not pending:
                return
            live = {_key(o) for o in kube.list(gvk)}
            for ns, name in pending - live:
                tr.cancel_expect({"metadata": {"namespace": ns, "name": name}})

        _collect(self.templates, TEMPLATES_GVK)
        _collect(self.config, CONFIG_GVK)
        with self._lock:
            items = list(self._constraints.items()) + list(self._data.items())
        for gvk, tr in items:
            _collect(tr, gvk)
        # a template canceled above can never deliver its constraints
        live_templates = kube.list(TEMPLATES_GVK)
        live_kinds = {template_constraint_kind(t) for t in live_templates}
        with self._lock:
            constraint_items = list(self._constraints.items())
        for gvk, tr in constraint_items:
            if gvk[2] not in live_kinds:
                tr.cancel_all()

    # ---- satisfaction -----------------------------------------------------

    def satisfied(self) -> bool:
        with self._lock:
            if self._satisfied:
                return True
        # templates gate constraints (ready_tracker.go:137-172: template
        # expectations must resolve before constraint kinds are authoritative)
        if not self.templates.satisfied():
            return False
        with self._lock:
            if not (self._constraints_populated and self._data_populated):
                return False
            trackers = list(self._constraints.values()) + list(self._data.values())
        if not all(t.satisfied() for t in trackers):
            return False
        if not self.config.satisfied():
            return False
        with self._lock:
            self._satisfied = True
        return True

    def wait_satisfied(self, timeout: float = 10.0, poll: float = 0.02) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.satisfied():
                return True
            time.sleep(poll)
        return self.satisfied()

    def pending_summary(self) -> Dict[str, list]:
        out = {}
        if not self.templates.satisfied():
            out["templates"] = sorted(self.templates.pending())
        with self._lock:
            items = list(self._constraints.items()) + list(self._data.items())
        for gvk, tr in items:
            if not tr.satisfied():
                out[str(gvk)] = sorted(tr.pending())
        return out
