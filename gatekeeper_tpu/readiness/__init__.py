from .tracker import ObjectTracker, Tracker  # noqa: F401
