"""Shared overload-response taxonomy for the proof harnesses (ISSUE 12).

`bench.py overload` and `tools/check_overload.py` both classify every
wire response into the docs/failure-modes.md taxonomy and compare
accepted verdicts against the interpreter oracle; the classification
rules (which HTTP/status-code combinations are a shed vs an expiry vs
an accepted admission, and how webhook deny messages normalize against
oracle messages) are load-bearing for BOTH the tier-1 conformance gate
and the recorded artifact — one copy, so they cannot drift apart.
"""

from __future__ import annotations

import json
import re
from typing import Optional, Tuple

ACCEPTED = "accepted"
SHED = "shed"
EXPIRED = "expired"
PROBLEM = "problem"

_DENY_PREFIX = re.compile(r"^\[denied by [^\]]+\] ")


def classify_response(status: int, data: bytes
                      ) -> Tuple[str, Optional[dict]]:
    """-> (ACCEPTED|SHED|EXPIRED|PROBLEM, parsed response|None).

    The taxonomy of docs/failure-modes.md: a 429 at the door or a
    200-wrapped code-429 verdict is a shed; a 200-wrapped code-504 is a
    deadline expiry; any other 200 is an accepted admission; everything
    else (502s, unparseable bodies, refusals WITHOUT an explicit
    allowed verdict) is unexplained."""
    if status not in (200, 429):
        return PROBLEM, None
    try:
        out = json.loads(data)["response"]
    except Exception:
        return PROBLEM, None
    code = (out.get("status") or {}).get("code")
    explicit = isinstance(out.get("allowed"), bool)
    if status == 429 or code == 429:
        return (SHED if explicit else PROBLEM), out
    if code == 504:
        return (EXPIRED if explicit else PROBLEM), out
    return ACCEPTED, out


def normalize_deny_messages(out: dict) -> list:
    """Sorted violation messages with the webhook's
    ``[denied by <constraint>] `` prefix stripped — the form oracle
    verdicts compare against.  Empty for allowed responses."""
    if out.get("allowed"):
        return []
    return sorted(
        _DENY_PREFIX.sub("", m)
        for m in (out.get("status") or {}).get("message", "").split("\n")
        if m
    )


def verdict_matches(out: dict, want: Tuple[bool, list]) -> bool:
    """One accepted response against its oracle verdict
    ``(allowed, sorted_messages)`` — allow/deny AND rendered message
    bytes must agree."""
    allowed = out["allowed"]
    o_allowed, o_msgs = want
    if allowed != o_allowed:
        return False
    return allowed or normalize_deny_messages(out) == list(o_msgs)
