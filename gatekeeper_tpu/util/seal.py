"""One trust model for on-disk derived state (AOT executables, snapshots).

Both persistence layers (ops/aotcache.py pickled executables,
snapshot/ packed-state directories) load bytes from disk that were
written by an earlier process and feed them to loaders that are NOT
safe against malicious input (pickle, np.load).  The shared seal here
closes the gap ADVICE flagged for the AOT cache: every artifact is
authenticated with an HMAC-SHA256 before it is parsed, so a writable
cache/snapshot directory alone is no longer enough to smuggle a
payload into the process — the attacker must also know the key.

Key derivation, in priority order:

1. ``GK_SEAL_KEY`` environment variable (operators: a per-deployment
   secret, e.g. projected from a Kubernetes Secret).  This is the
   production configuration; with it the seal is a real authentication
   boundary.
2. Fallback: a digest of this package's source fingerprint.  This is
   NOT secret (anyone holding the image can derive it) — it still
   rejects artifacts written by a different build and any accidental
   corruption/truncation, and keeps the artifact format identical so
   enabling a real key later is a pure config change.  The residual
   trust assumption (documented in docs/snapshots.md) is that the
   cache directory is only writable by the gatekeeper pod itself,
   which is why both layers also create their directories 0700.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
from typing import Optional

_code_fp: Optional[str] = None


def code_fingerprint() -> str:
    """Digest of every source file in this package: derived state written
    by a build whose code changed must never be reused (it would silently
    reproduce pre-fix semantics)."""
    global _code_fp
    if _code_fp is None:
        h = hashlib.sha256()
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for root, _dirs, files in sorted(os.walk(pkg)):
            for f in sorted(files):
                if f.endswith((".py", ".cpp")):
                    path = os.path.join(root, f)
                    h.update(f.encode())
                    try:
                        with open(path, "rb") as fh:
                            h.update(fh.read())
                    except OSError:
                        pass
        _code_fp = h.hexdigest()
    return _code_fp


def seal_key() -> bytes:
    """The HMAC key shared by every sealed-artifact layer."""
    k = os.environ.get("GK_SEAL_KEY", "")
    if k:
        return k.encode()
    return hashlib.sha256(
        b"gatekeeper-tpu-seal:" + code_fingerprint().encode()
    ).digest()


def seal(data: bytes) -> str:
    """Hex HMAC-SHA256 tag over `data` under the shared key."""
    return _hmac.new(seal_key(), data, hashlib.sha256).hexdigest()


def stable_seal_key() -> bytes:
    """Key for sealed artifacts that are SOURCE data meant to outlive
    builds (decision-log segments, obs/decisionlog.py): ``GK_SEAL_KEY``
    when set (the real authentication boundary, same variable as
    ``seal_key``), else a fixed package constant.  Unlike ``seal_key``'s
    code-fingerprint fallback — correct for DERIVED state, which must
    never cross a build — a decision archive's whole point is to be
    replayed against a LATER engine (tools/replay_decisions.py), so its
    fallback key must not change when the source does.  Without a real
    key either fallback is derivable from the image; the unkeyed seal
    detects corruption, reordering and truncation, not a deliberate
    re-signer (docs/decision-logs.md documents the posture)."""
    k = os.environ.get("GK_SEAL_KEY", "")
    if k:
        return k.encode()
    return hashlib.sha256(b"gatekeeper-tpu-seal:source-data:v1").digest()


def stable_seal(data: bytes) -> str:
    """Hex HMAC-SHA256 tag over `data` under the build-stable key."""
    return _hmac.new(stable_seal_key(), data, hashlib.sha256).hexdigest()


def verify(data: bytes, tag: str) -> bool:
    """Constant-time check of `tag` against `data`; False on any
    malformed tag rather than raising — callers treat a bad seal as a
    cache miss / cold-start fallback, never an error path."""
    try:
        return _hmac.compare_digest(seal(data), str(tag))
    except Exception:
        return False


def secure_makedirs(path: str) -> None:
    """mkdir -p with 0700 on every directory this process creates: the
    artifacts under it gate what the process will deserialize, so group/
    world write (or read — the HMAC fallback key is derivable) is never
    acceptable."""
    os.makedirs(path, mode=0o700, exist_ok=True)
    try:
        os.chmod(path, 0o700)  # pre-existing dir: tighten, don't trust
    except OSError:
        pass
