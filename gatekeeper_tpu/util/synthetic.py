"""Synthetic benchmark workloads.

Generates ConstraintTemplates across the policy families that dominate real
Gatekeeper deployments (label requirements, privileged/host flags, port
ranges, image-prefix allowlists, field-key allowlists — the same families as
the reference's PSP/demo corpus, with original Rego), plus synthetic cluster
resources with a controlled violation rate.  Used by bench.py and
__graft_entry__.py; mirrors the BASELINE.md synthetic config
(500 templates x 100k resources).
"""

from __future__ import annotations

import random
from typing import Dict, List, Tuple

FAMILIES = [
    # (name stem, rego builder, params builder)
    "labelreq",
    "privflag",
    "hostflags",
    "portrange",
    "imageprefix",
    "fieldkeys",
]


def _rego_labelreq(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg, "details": {{"missing": missing}}}}] {{
  have := {{k | input.review.object.metadata.labels[k]}}
  want := {{k | k := input.parameters.required[_]}}
  missing := want - have
  count(missing) > 0
  msg := sprintf("missing required labels: %v", [missing])
}}
"""


def _rego_privflag(pkg: str) -> str:
    return f"""
package {pkg}

workloads[c] {{
  c := input.review.object.spec.containers[_]
}}

workloads[c] {{
  c := input.review.object.spec.initContainers[_]
}}

violation[{{"msg": msg}}] {{
  c := workloads[_]
  c.securityContext.privileged
  msg := sprintf("privileged container forbidden: %v", [c.name])
}}
"""


def _rego_hostflags(pkg: str) -> str:
    return f"""
package {pkg}

uses_host_namespace(o) {{
  o.spec.hostPID
}}

uses_host_namespace(o) {{
  o.spec.hostIPC
}}

violation[{{"msg": msg}}] {{
  uses_host_namespace(input.review.object)
  msg := sprintf("host namespaces forbidden: %v", [input.review.object.metadata.name])
}}
"""


def _rego_portrange(pkg: str) -> str:
    return f"""
package {pkg}

bad_port(o) {{
  p := o.spec.containers[_].ports[_].hostPort
  p < input.parameters.low
}}

bad_port(o) {{
  p := o.spec.containers[_].ports[_].hostPort
  p > input.parameters.high
}}

violation[{{"msg": msg}}] {{
  bad_port(input.review.object)
  msg := sprintf("hostPort outside allowed range [%v, %v]", [input.parameters.low, input.parameters.high])
}}
"""


def _rego_imageprefix(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg}}] {{
  c := input.review.object.spec.containers[_]
  ok := [hit | p = input.parameters.prefixes[_]; hit = startswith(c.image, p)]
  not any(ok)
  msg := sprintf("image %v not from an allowed registry %v", [c.image, input.parameters.prefixes])
}}
"""


def _rego_fieldkeys(pkg: str) -> str:
    return f"""
package {pkg}

allowed(fields) {{
  input.parameters.kinds[_] == "*"
}}

allowed(fields) {{
  allow := {{k | k = input.parameters.kinds[_]}}
  extra := fields - allow
  count(extra) == 0
}}

violation[{{"msg": msg}}] {{
  fields := {{k | input.review.object.spec.volumes[_][k]; k != "name"}}
  not allowed(fields)
  msg := sprintf("volume types %v not allowed", [fields])
}}
"""


_REGO = {
    "labelreq": _rego_labelreq,
    "privflag": _rego_privflag,
    "hostflags": _rego_hostflags,
    "portrange": _rego_portrange,
    "imageprefix": _rego_imageprefix,
    "fieldkeys": _rego_fieldkeys,
}


def _params(family: str, rng: random.Random) -> dict:
    # Compliant resources must satisfy every constraint clone (real clusters
    # converge to compliance), so allowlists always contain the values the
    # good pods use.
    if family == "labelreq":
        return {"required": rng.sample(["owner", "team", "env", "cost", "tier"], 2)}
    if family == "portrange":
        return {"low": rng.choice([1, 80, 100]), "high": rng.choice([30000, 60000])}
    if family == "imageprefix":
        return {"prefixes": ["registry.corp/"] + rng.sample(
            ["gcr.io/prod/", "docker.io/library/", "quay.io/app/"], 2
        )}
    if family == "fieldkeys":
        return {"kinds": ["emptyDir"] + rng.sample(
            ["configMap", "secret", "projected"], 2
        )}
    return {}


def make_templates(n: int, seed: int = 0) -> Tuple[List[dict], List[dict]]:
    """n templates cycling the families (each its own CRD kind) + one
    constraint per template."""
    rng = random.Random(seed)
    templates, constraints = [], []
    for i in range(n):
        family = FAMILIES[i % len(FAMILIES)]
        kind = f"Bench{family.capitalize()}{i}"
        pkg = f"bench{family}{i}"
        templates.append(
            {
                "apiVersion": "templates.gatekeeper.sh/v1beta1",
                "kind": "ConstraintTemplate",
                "metadata": {"name": kind.lower()},
                "spec": {
                    "crd": {"spec": {"names": {"kind": kind}}},
                    "targets": [
                        {
                            "target": "admission.k8s.gatekeeper.sh",
                            "rego": _REGO[family](pkg),
                        }
                    ],
                },
            }
        )
        constraints.append(
            {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind,
                "metadata": {"name": f"c-{kind.lower()}"},
                "spec": {
                    "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
                    "parameters": _params(family, rng),
                },
            }
        )
    return templates, constraints


def make_pods(n: int, seed: int = 1, violation_rate: float = 0.05) -> List[dict]:
    """Synthetic Pods; ~violation_rate of them trip at least one family."""
    rng = random.Random(seed)
    pods = []
    for i in range(n):
        bad = rng.random() < violation_rate
        containers = []
        for j in range(rng.randint(1, 3)):
            ctr = {
                "name": f"app-{j}",
                "image": (
                    "evil.io/x:latest"
                    if bad and rng.random() < 0.5
                    else "registry.corp/svc:" + str(rng.randint(1, 40))
                ),
            }
            if bad and rng.random() < 0.3:
                ctr["securityContext"] = {"privileged": True}
            if rng.random() < 0.3:
                ctr["ports"] = [
                    {"hostPort": 31337 if bad and rng.random() < 0.5 else 8080}
                ]
            containers.append(ctr)
        spec: Dict = {"containers": containers}
        if bad and rng.random() < 0.2:
            spec["hostPID"] = True
        if rng.random() < 0.3:
            spec["volumes"] = [
                {"name": "v0",
                 ("nfs" if bad and rng.random() < 0.4 else "emptyDir"): {}}
            ]
        labels = {"owner": "core", "team": "plat", "env": "prod",
                  "cost": "cc1", "tier": "t1"}
        if bad and rng.random() < 0.4:
            labels.pop(rng.choice(list(labels)))
        pods.append(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"pod-{i}",
                    "namespace": f"ns-{i % 50}",
                    "labels": labels,
                },
                "spec": spec,
            }
        )
    return pods


def build_driver(n_templates: int, n_resources: int, seed: int = 0):
    """A TpuDriver loaded with the synthetic workload (via the Client so all
    validation paths run)."""
    from ..ops.driver import TpuDriver

    return _load_client(TpuDriver(), n_templates, n_resources, seed)


def build_oracle(n_templates: int, n_resources: int, seed: int = 0):
    """An InterpDriver client loaded with the SAME synthetic corpus
    build_driver creates — the interpreter oracle for byte-parity checks.
    It must be its own instance: an unbound InterpDriver method call on a
    TpuDriver would dispatch polymorphically right back onto the device
    path."""
    from ..client.drivers import InterpDriver

    return _load_client(InterpDriver(), n_templates, n_resources, seed)


def _load_client(driver, n_templates: int, n_resources: int, seed: int):
    from ..client.client import Client

    templates, constraints = make_templates(n_templates, seed)
    client = Client(driver=driver)
    for t in templates:
        client.add_template(t)
    for c in constraints:
        client.add_constraint(c)
    for p in make_pods(n_resources, seed + 1):
        client.add_data(p)
    return client


def audit_result_sig(results):
    """Canonical order-independent signature of audit results for
    byte-parity comparisons (constraint kind+name, rendered message,
    resource name).  The ONE definition shared by the mesh parity tool,
    the mesh tests and bench.py mesh_curve — so all three gate on the
    same notion of parity."""
    return sorted(
        (
            r.constraint.get("kind", ""),
            (r.constraint.get("metadata") or {}).get("name", ""),
            r.msg,
            str((r.review.get("object") or {}).get("metadata", {})
                .get("name")),
        )
        for r in results
    )
