"""Synthetic benchmark workloads.

Generates ConstraintTemplates across the policy families that dominate real
Gatekeeper deployments (label requirements, privileged/host flags, port
ranges, image-prefix allowlists, field-key allowlists — the same families as
the reference's PSP/demo corpus, with original Rego), plus synthetic cluster
resources with a controlled violation rate.  Used by bench.py and
__graft_entry__.py; mirrors the BASELINE.md synthetic config
(500 templates x 100k resources).
"""

from __future__ import annotations

import random
from typing import Dict, List, Tuple

FAMILIES = [
    # (name stem, rego builder, params builder)
    "labelreq",
    "privflag",
    "hostflags",
    "portrange",
    "imageprefix",
    "fieldkeys",
]


def _rego_labelreq(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg, "details": {{"missing": missing}}}}] {{
  have := {{k | input.review.object.metadata.labels[k]}}
  want := {{k | k := input.parameters.required[_]}}
  missing := want - have
  count(missing) > 0
  msg := sprintf("missing required labels: %v", [missing])
}}
"""


def _rego_privflag(pkg: str) -> str:
    return f"""
package {pkg}

workloads[c] {{
  c := input.review.object.spec.containers[_]
}}

workloads[c] {{
  c := input.review.object.spec.initContainers[_]
}}

violation[{{"msg": msg}}] {{
  c := workloads[_]
  c.securityContext.privileged
  msg := sprintf("privileged container forbidden: %v", [c.name])
}}
"""


def _rego_hostflags(pkg: str) -> str:
    return f"""
package {pkg}

uses_host_namespace(o) {{
  o.spec.hostPID
}}

uses_host_namespace(o) {{
  o.spec.hostIPC
}}

violation[{{"msg": msg}}] {{
  uses_host_namespace(input.review.object)
  msg := sprintf("host namespaces forbidden: %v", [input.review.object.metadata.name])
}}
"""


def _rego_portrange(pkg: str) -> str:
    return f"""
package {pkg}

bad_port(o) {{
  p := o.spec.containers[_].ports[_].hostPort
  p < input.parameters.low
}}

bad_port(o) {{
  p := o.spec.containers[_].ports[_].hostPort
  p > input.parameters.high
}}

violation[{{"msg": msg}}] {{
  bad_port(input.review.object)
  msg := sprintf("hostPort outside allowed range [%v, %v]", [input.parameters.low, input.parameters.high])
}}
"""


def _rego_imageprefix(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg}}] {{
  c := input.review.object.spec.containers[_]
  ok := [hit | p = input.parameters.prefixes[_]; hit = startswith(c.image, p)]
  not any(ok)
  msg := sprintf("image %v not from an allowed registry %v", [c.image, input.parameters.prefixes])
}}
"""


def _rego_fieldkeys(pkg: str) -> str:
    return f"""
package {pkg}

allowed(fields) {{
  input.parameters.kinds[_] == "*"
}}

allowed(fields) {{
  allow := {{k | k = input.parameters.kinds[_]}}
  extra := fields - allow
  count(extra) == 0
}}

violation[{{"msg": msg}}] {{
  fields := {{k | input.review.object.spec.volumes[_][k]; k != "name"}}
  not allowed(fields)
  msg := sprintf("volume types %v not allowed", [fields])
}}
"""


_REGO = {
    "labelreq": _rego_labelreq,
    "privflag": _rego_privflag,
    "hostflags": _rego_hostflags,
    "portrange": _rego_portrange,
    "imageprefix": _rego_imageprefix,
    "fieldkeys": _rego_fieldkeys,
}


def _params(family: str, rng: random.Random) -> dict:
    # Compliant resources must satisfy every constraint clone (real clusters
    # converge to compliance), so allowlists always contain the values the
    # good pods use.
    if family == "labelreq":
        return {"required": rng.sample(["owner", "team", "env", "cost", "tier"], 2)}
    if family == "portrange":
        return {"low": rng.choice([1, 80, 100]), "high": rng.choice([30000, 60000])}
    if family == "imageprefix":
        return {"prefixes": ["registry.corp/"] + rng.sample(
            ["gcr.io/prod/", "docker.io/library/", "quay.io/app/"], 2
        )}
    if family == "fieldkeys":
        return {"kinds": ["emptyDir"] + rng.sample(
            ["configMap", "secret", "projected"], 2
        )}
    return {}


def make_templates(n: int, seed: int = 0) -> Tuple[List[dict], List[dict]]:
    """n templates cycling the families (each its own CRD kind) + one
    constraint per template."""
    rng = random.Random(seed)
    templates, constraints = [], []
    for i in range(n):
        family = FAMILIES[i % len(FAMILIES)]
        kind = f"Bench{family.capitalize()}{i}"
        pkg = f"bench{family}{i}"
        templates.append(
            {
                "apiVersion": "templates.gatekeeper.sh/v1beta1",
                "kind": "ConstraintTemplate",
                "metadata": {"name": kind.lower()},
                "spec": {
                    "crd": {"spec": {"names": {"kind": kind}}},
                    "targets": [
                        {
                            "target": "admission.k8s.gatekeeper.sh",
                            "rego": _REGO[family](pkg),
                        }
                    ],
                },
            }
        )
        constraints.append(
            {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind,
                "metadata": {"name": f"c-{kind.lower()}"},
                "spec": {
                    "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
                    "parameters": _params(family, rng),
                },
            }
        )
    return templates, constraints


def make_pods(n: int, seed: int = 1, violation_rate: float = 0.05) -> List[dict]:
    """Synthetic Pods; ~violation_rate of them trip at least one family."""
    rng = random.Random(seed)
    pods = []
    for i in range(n):
        bad = rng.random() < violation_rate
        containers = []
        for j in range(rng.randint(1, 3)):
            ctr = {
                "name": f"app-{j}",
                "image": (
                    "evil.io/x:latest"
                    if bad and rng.random() < 0.5
                    else "registry.corp/svc:" + str(rng.randint(1, 40))
                ),
            }
            if bad and rng.random() < 0.3:
                ctr["securityContext"] = {"privileged": True}
            if rng.random() < 0.3:
                ctr["ports"] = [
                    {"hostPort": 31337 if bad and rng.random() < 0.5 else 8080}
                ]
            containers.append(ctr)
        spec: Dict = {"containers": containers}
        if bad and rng.random() < 0.2:
            spec["hostPID"] = True
        if rng.random() < 0.3:
            spec["volumes"] = [
                {"name": "v0",
                 ("nfs" if bad and rng.random() < 0.4 else "emptyDir"): {}}
            ]
        labels = {"owner": "core", "team": "plat", "env": "prod",
                  "cost": "cc1", "tier": "t1"}
        if bad and rng.random() < 0.4:
            labels.pop(rng.choice(list(labels)))
        pods.append(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"pod-{i}",
                    "namespace": f"ns-{i % 50}",
                    "labels": labels,
                },
                "spec": spec,
            }
        )
    return pods


def build_driver(n_templates: int, n_resources: int, seed: int = 0):
    """A TpuDriver loaded with the synthetic workload (via the Client so all
    validation paths run)."""
    from ..ops.driver import TpuDriver

    return _load_client(TpuDriver(), n_templates, n_resources, seed)


def build_oracle(n_templates: int, n_resources: int, seed: int = 0):
    """An InterpDriver client loaded with the SAME synthetic corpus
    build_driver creates — the interpreter oracle for byte-parity checks.
    It must be its own instance: an unbound InterpDriver method call on a
    TpuDriver would dispatch polymorphically right back onto the device
    path."""
    from ..client.drivers import InterpDriver

    return _load_client(InterpDriver(), n_templates, n_resources, seed)


def _load_client(driver, n_templates: int, n_resources: int, seed: int):
    from ..client.client import Client

    templates, constraints = make_templates(n_templates, seed)
    client = Client(driver=driver)
    for t in templates:
        client.add_template(t)
    for c in constraints:
        client.add_constraint(c)
    for p in make_pods(n_resources, seed + 1):
        client.add_data(p)
    return client


# ---------------------------------------------------------------------------
# Referential corpus (cross-resource join plans, ops/joinkernel.py)
# ---------------------------------------------------------------------------

REF_FAMILIES = ["uniquehost", "requiredclass", "teamquota"]


def _rego_uniquehost(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg}}] {{
  host := input.review.object.spec.rules[_].host
  other := data.inventory.namespace[_][_]["Ingress"][_]
  otherhost := other.spec.rules[_].host
  host == otherhost
  not identical(other, input.review)
  msg := sprintf("duplicate ingress host: %v", [host])
}}

identical(obj, review) {{
  obj.metadata.namespace == review.object.metadata.namespace
  obj.metadata.name == review.object.metadata.name
}}
"""


def _rego_requiredclass(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg}}] {{
  class := input.review.object.spec.storageClassName
  not class_exists(class)
  msg := sprintf("storage class %v does not exist", [class])
}}

class_exists(name) {{
  sc := data.inventory.cluster[_]["StorageClass"][_]
  sc.metadata.name == name
}}
"""


def _rego_teamquota(pkg: str) -> str:
    return f"""
package {pkg}

violation[{{"msg": msg}}] {{
  team := input.review.object.metadata.labels.team
  n := count({{[ns, ident] | p := data.inventory.namespace[ns][_]["Pod"][ident]; p.metadata.labels.team == team}})
  n > input.parameters.limit
  msg := sprintf("team %v has %v pods (limit %v)", [team, n, input.parameters.limit])
}}
"""


_REF_REGO = {
    "uniquehost": _rego_uniquehost,
    "requiredclass": _rego_requiredclass,
    "teamquota": _rego_teamquota,
}

_REF_MATCH = {
    "uniquehost": [{"apiGroups": ["networking.k8s.io"],
                    "kinds": ["Ingress"]}],
    "requiredclass": [{"apiGroups": ["*"],
                       "kinds": ["PersistentVolumeClaim"]}],
    "teamquota": [{"apiGroups": [""], "kinds": ["Pod"]}],
}


def make_referential_templates(n: int, seed: int = 0):
    """n referential templates cycling the three join families (each its
    own CRD kind, so clones batch on the constraint axis of one shared
    program structure) + one constraint per template."""
    rng = random.Random(seed)
    templates, constraints = [], []
    for i in range(n):
        family = REF_FAMILIES[i % len(REF_FAMILIES)]
        kind = f"Ref{family.capitalize()}{i}"
        pkg = f"ref{family}{i}"
        templates.append(
            {
                "apiVersion": "templates.gatekeeper.sh/v1beta1",
                "kind": "ConstraintTemplate",
                "metadata": {"name": kind.lower()},
                "spec": {
                    "crd": {"spec": {"names": {"kind": kind}}},
                    "targets": [
                        {
                            "target": "admission.k8s.gatekeeper.sh",
                            "rego": _REF_REGO[family](pkg),
                        }
                    ],
                },
            }
        )
        params = (
            {"limit": rng.choice([1, 2, 3, 5])}
            if family == "teamquota" else {}
        )
        constraints.append(
            {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind,
                "metadata": {"name": f"c-{kind.lower()}"},
                "spec": {
                    "match": {"kinds": _REF_MATCH[family]},
                    "parameters": params,
                },
            }
        )
    return templates, constraints


def make_referential_objects(n: int, seed: int = 1) -> List[dict]:
    """A mixed inventory the three join families bite on: Ingresses with
    deliberately colliding hosts, PVCs referencing (sometimes dangling)
    StorageClasses, and Pods with team labels — a few of them integer
    values, pinning the typed interned-key normalization (an int team
    must never pool with its string twin)."""
    rng = random.Random(seed)
    objs: List[dict] = [
        {
            "apiVersion": "storage.k8s.io/v1",
            "kind": "StorageClass",
            "metadata": {"name": scn},
        }
        for scn in ("standard", "fast", "gold")
    ]
    # realistic clusters converge to compliance: most hosts are unique
    # (a small shared pool supplies deliberate duplicates), most PVC
    # references resolve, most teams sit under quota.  Violation rate
    # lands around a few percent per family.
    dup_pool = [f"app-{k}.corp.io" for k in range(3)]
    for i in range(n):
        ns = f"ns-{i % 10}"
        pick = i % 3
        if pick == 0:
            if rng.random() < 0.04:
                rules = [{"host": rng.choice(dup_pool)}]
            else:
                rules = [{"host": f"svc-{i}.corp.io"}]
            if rng.random() < 0.2:
                rules.append({"host": f"alt-{i}.corp.io"})
            objs.append({
                "apiVersion": "networking.k8s.io/v1",
                "kind": "Ingress",
                "metadata": {"name": f"ing-{i}", "namespace": ns},
                "spec": {"rules": rules},
            })
        elif pick == 1:
            cls = (
                f"missing-{i % 7}" if rng.random() < 0.05
                else rng.choice(["standard", "fast", "gold"])
            )
            objs.append({
                "apiVersion": "v1",
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": f"pvc-{i}", "namespace": ns},
                "spec": {"storageClassName": cls},
            })
        else:
            # "crowded" (and the int-vs-str twins) exceed the quota on
            # bigger corpora; the per-pod teams stay under it
            r = rng.random()
            if r < 0.015:
                team = "crowded"
            elif r < 0.02:
                team = 5
            elif r < 0.025:
                team = "5"
            else:
                team = f"team-{i}"
            objs.append({
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"pod-{i}", "namespace": ns,
                    "labels": {"team": team},
                },
                "spec": {"containers": [{"name": "c", "image": "r/i:1"}]},
            })
    return objs


def _load_referential(driver, n_templates: int, n_resources: int,
                      seed: int):
    from ..client.client import Client

    templates, constraints = make_referential_templates(n_templates, seed)
    client = Client(driver=driver)
    for t in templates:
        client.add_template(t)
    for c in constraints:
        client.add_constraint(c)
    for o in make_referential_objects(n_resources, seed + 1):
        client.add_data(o)
    return client


def build_referential_driver(n_templates: int, n_resources: int,
                             seed: int = 0):
    """A TpuDriver loaded with the referential workload."""
    from ..ops.driver import TpuDriver

    return _load_referential(TpuDriver(), n_templates, n_resources, seed)


def build_referential_oracle(n_templates: int, n_resources: int,
                             seed: int = 0):
    """The interpreter-oracle twin over the identical corpus (own
    instance — see build_oracle)."""
    from ..client.drivers import InterpDriver

    return _load_referential(InterpDriver(), n_templates, n_resources, seed)


def audit_result_sig(results):
    """Canonical order-independent signature of audit results for
    byte-parity comparisons (constraint kind+name, rendered message,
    resource name).  The ONE definition shared by the mesh parity tool,
    the mesh tests and bench.py mesh_curve — so all three gate on the
    same notion of parity."""
    return sorted(
        (
            r.constraint.get("kind", ""),
            (r.constraint.get("metadata") or {}).get("name", ""),
            r.msg,
            str((r.review.get("object") or {}).get("metadata", {})
                .get("name")),
        )
        for r in results
    )
