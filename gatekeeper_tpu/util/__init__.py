"""Shared small utilities (reference pkg/util/).

- enforcement-action enum + validation (enforcement_action.go:11-47)
- GVK packing of reconcile requests for type-erased controllers (pack.go:17-57)
- pod identity from env (pod_info.go:5-21)
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

DENY = "deny"
DRYRUN = "dryrun"
UNRECOGNIZED = "unrecognized"

SUPPORTED_ENFORCEMENT_ACTIONS = (DENY, DRYRUN)
KNOWN_ENFORCEMENT_ACTIONS = (DENY, DRYRUN, UNRECOGNIZED)


class EnforcementActionError(ValueError):
    pass


def validate_enforcement_action(action: str) -> None:
    """enforcement_action.go:20-27: only deny/dryrun are supported."""
    if action not in SUPPORTED_ENFORCEMENT_ACTIONS:
        raise EnforcementActionError(
            f"could not find the provided enforcementAction value within the "
            f"supported list {list(SUPPORTED_ENFORCEMENT_ACTIONS)}"
        )


def get_enforcement_action(constraint: dict) -> str:
    """enforcement_action.go:29-46: default deny; anything unsupported is
    classified as 'unrecognized' (never an error)."""
    spec = constraint.get("spec")
    if not isinstance(spec, dict):
        spec = {}
    action = spec.get("enforcementAction") or DENY
    if not isinstance(action, str):
        return UNRECOGNIZED
    if action not in SUPPORTED_ENFORCEMENT_ACTIONS:
        return UNRECOGNIZED
    return action


# ---- request packing (pack.go) -------------------------------------------
#
# Dynamic (type-erased) controllers receive events for many GVKs over one
# queue; the GVK rides inside the request name as "gvk:Kind.Version.Group:Name".


def pack_request(gvk: Tuple[str, str, str], name: str, namespace: str = "") -> Tuple[str, str]:
    """EventPacker.Map (pack.go:33-57) -> (packed_name, namespace)."""
    group, version, kind = gvk
    version = version or "v1"
    encoded = f"{kind}.{version}.{group}"
    return f"gvk:{encoded}:{name}", namespace


def unpack_request(packed_name: str, namespace: str = ""):
    """UnpackRequest (pack.go:17-31) -> (gvk, name, namespace)."""
    fields = packed_name.split(":", 2)
    if len(fields) != 3 or fields[0] != "gvk":
        raise ValueError(f"invalid packed name: {packed_name}")
    parts = fields[1].split(".", 2)
    if len(parts) != 3:
        raise ValueError(f"unable to parse gvk: {fields[1]}")
    kind, version, group = parts
    return (group, version, kind), fields[2], namespace


# ---- pod identity (pod_info.go) ------------------------------------------


def get_pod_name() -> str:
    return os.environ.get("POD_NAME", "")


def get_id() -> str:
    return get_pod_name()


def get_namespace() -> str:
    return os.environ.get("POD_NAMESPACE", "gatekeeper-system")


# ---- fleet replica identity (docs/fleet.md) -------------------------------
#
# One process = one serving replica.  The id is stamped into root spans,
# the replica-labelled metrics series, the SLO engine's /statusz payload
# and every "started" log line, so a fleet's telemetry separates by
# replica without relying on scrape-time instance labels.  Empty means
# "not part of a fleet" (single-process deployments stay label-free).

_replica_id: Optional[str] = None


def set_replica_id(rid: str) -> None:
    global _replica_id
    _replica_id = str(rid or "")


def replica_id() -> str:
    """The process's fleet replica id: --replica-id, else $GK_REPLICA_ID,
    else empty."""
    if _replica_id is not None:
        return _replica_id
    return os.environ.get("GK_REPLICA_ID", "")


def join_thread(thread, timeout: float, what: str = "") -> bool:
    """Bounded join with a post-join liveness check: returns True when
    the thread actually exited, False (and logs a warning naming it) when
    it is still alive after `timeout` — the caller proceeds with shutdown
    instead of hanging behind a wedged worker (the PR 8 wedge class; the
    static twin of this rule is gklint's `bare-join`).  None threads are
    trivially 'joined'."""
    if thread is None:
        return True
    thread.join(timeout=timeout)
    if thread.is_alive():
        import logging

        logging.getLogger("gatekeeper.util").warning(
            "thread %s still alive %.1fs after join%s — proceeding with "
            "shutdown; it is daemonized and cannot pin exit",
            thread.name, timeout, f" ({what})" if what else "",
        )
        return False
    return True


def close_listener(server, thread) -> None:
    """Tear down a socketserver-based listener for an idempotent
    ``start()``: ``shutdown()`` only when its serve_forever thread
    actually runs (on a loop that never started it would block forever),
    then close the socket.  Callers null their own references afterwards
    — a double ``start()`` replaces the previous listener instead of
    leaking its thread and socket (the WebhookServer / MetricsExporter
    contract; used by HealthServer, ProfileServer and the fleet
    FrontDoor)."""
    if server is None:
        return
    if thread is not None and thread.is_alive():
        server.shutdown()
    server.server_close()


def nested_get(obj: Any, *path: str, default: Any = None) -> Any:
    """unstructured.Nested* analogue: walk dict path, default on miss."""
    node = obj
    for seg in path:
        if not isinstance(node, dict) or seg not in node:
            return default
        node = node[seg]
    return node
