"""JAX API compatibility shims.

The mesh audit path is written against the modern spelling
``jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)``.
On jax 0.4.x that symbol lives at ``jax.experimental.shard_map.shard_map``
and the replication-check kwarg is named ``check_rep`` — without this shim
every sharded sweep raises ``AttributeError`` at trace time and the circuit
breaker silently degrades the whole mesh family to the interpreter tier
(the seed-state failure mode of test_mesh / test_race_determinism /
test_audit_topk mesh variants).

One resolver, used by BOTH shard_map call sites (ops/driver.py
_fused_audit_mesh_fn and parallel/multihost.py multihost_capped_sweep),
so the two paths can never drift onto different underlying APIs.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map"]


def _resolve():
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, "check_vma"
    from jax.experimental.shard_map import shard_map as legacy

    return legacy, "check_rep"


_SHARD_MAP, _CHECK_KW = _resolve()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the modern signature on every supported jax.

    ``check_vma`` maps onto 0.4.x's ``check_rep`` (same meaning: verify
    per-output replication annotations; both paths here disable it — the
    fused audit body mixes replicated and row-sharded outputs the checker
    cannot type).  ``None`` keeps the backend default.
    """
    kwargs = {}
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
