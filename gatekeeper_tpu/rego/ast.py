"""AST for the Rego subset compiled by this framework.

The grammar covers the policy corpus shipped with the reference
(demo/, library/, pkg/webhook/testdata/, test/bats/tests/ under
/root/reference): multi-clause rules, functions (including constant-argument
clauses), partial set/object rules, array/set/object comprehensions, negation,
refs with variable operands, infix arithmetic/comparison/set operators, and
`some` declarations, import aliasing, `else` clause chains, and `with`
modifiers on input[...] / data.inventory[...] (OPA v0.21 restricts `with`
to input and base documents; the inventory is this engine's only base
document — the hook shim and constraint-matching library that use `with`
in the reference are implemented natively in gatekeeper_tpu.target /
gatekeeper_tpu.client).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple


class Node:
    __slots__ = ()


# --------------------------------------------------------------------------
# Terms
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Scalar(Node):
    value: Any  # None | bool | int | float | str


@dataclass(frozen=True)
class Var(Node):
    name: str

    @property
    def is_wildcard(self) -> bool:
        return self.name.startswith("$")


@dataclass(frozen=True)
class Ref(Node):
    """head[op0][op1]... — head is a Var; dotted access is a Scalar operand."""

    head: Var
    operands: Tuple[Node, ...]


@dataclass(frozen=True)
class Call(Node):
    """Function application: builtin (dotted path) or user function."""

    path: Tuple[str, ...]
    args: Tuple[Node, ...]


@dataclass(frozen=True)
class ArrayTerm(Node):
    items: Tuple[Node, ...]


@dataclass(frozen=True)
class SetTerm(Node):
    items: Tuple[Node, ...]


@dataclass(frozen=True)
class ObjectTerm(Node):
    pairs: Tuple[Tuple[Node, Node], ...]


@dataclass(frozen=True)
class ArrayCompr(Node):
    head: Node
    body: "Body"


@dataclass(frozen=True)
class SetCompr(Node):
    head: Node
    body: "Body"


@dataclass(frozen=True)
class ObjectCompr(Node):
    key: Node
    value: Node
    body: "Body"


@dataclass(frozen=True)
class BinOp(Node):
    op: str  # == != < <= > >= + - * / % | &
    lhs: Node
    rhs: Node


@dataclass(frozen=True)
class UnaryMinus(Node):
    operand: Node


# --------------------------------------------------------------------------
# Statements / rules
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Expr(Node):
    """One body statement."""

    kind: str  # "term" | "unify" | "assign" | "not" | "some"
    terms: Tuple[Node, ...]  # term: (t,); unify/assign: (lhs, rhs); not: (Expr,)
    loc: Tuple[int, int] = (0, 0)
    # `with` modifiers: ((target path, value term), ...).  Targets are
    # restricted to input[...] and data.inventory[...] — OPA v0.21 only
    # supports `with` on input and base documents, and this engine's only
    # base document is the inventory.
    withs: Tuple[Tuple[Tuple[str, ...], Node], ...] = ()


Body = Tuple[Expr, ...]


@dataclass(frozen=True)
class Rule(Node):
    name: str
    args: Optional[Tuple[Node, ...]]  # function params (terms; may be scalars)
    key: Optional[Node]  # partial set/object key term
    value: Optional[Node]  # head value term (None => true)
    body: Body
    is_default: bool = False
    loc: Tuple[int, int] = (0, 0)
    # `else` chain: the next clause, tried only if this clause's body fails
    # (OPA else semantics; valid on complete rules and functions only).
    els: Optional["Rule"] = None

    @property
    def is_function(self) -> bool:
        return self.args is not None

    @property
    def is_partial_set(self) -> bool:
        return self.key is not None and self.value is None

    @property
    def is_partial_object(self) -> bool:
        return self.key is not None and self.value is not None


@dataclass
class Module(Node):
    package: Tuple[str, ...]  # e.g. ("k8srequiredlabels",) or ("lib", "helpers")
    rules: Tuple[Rule, ...] = field(default_factory=tuple)
    source: str = ""

    def rules_named(self, name: str):
        return [r for r in self.rules if r.name == name]


class RegoError(Exception):
    """Parse/compile error with location info."""

    def __init__(self, msg: str, line: int = 0, col: int = 0):
        self.line, self.col = line, col
        super().__init__(f"{msg} (line {line}, col {col})" if line else msg)


class RegoParseError(RegoError):
    pass


class RegoCompileError(RegoError):
    pass
