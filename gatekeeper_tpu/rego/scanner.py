"""Tokenizer for the Rego subset.

Newlines are significant statement separators in rule bodies, so NEWLINE
tokens are emitted; the parser decides where they matter.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List

from .ast import RegoParseError

KEYWORDS = {
    "package",
    "import",
    "default",
    "not",
    "true",
    "false",
    "null",
    "as",
    "with",
    "some",
    "else",
    "set(",  # pseudo, never matched as ident
}

# Longest-match-first punctuation / operators.
_PUNCT = [
    ":=",
    "==",
    "!=",
    "<=",
    ">=",
    "{",
    "}",
    "[",
    "]",
    "(",
    ")",
    ",",
    ":",
    ";",
    ".",
    "|",
    "&",
    "=",
    "<",
    ">",
    "+",
    "-",
    "*",
    "/",
    "%",
]


@dataclass
class Token:
    kind: str  # ident kw number string punct newline eof
    value: Any
    line: int
    col: int

    def __repr__(self):
        return f"Token({self.kind},{self.value!r}@{self.line}:{self.col})"


def scan(src: str) -> List[Token]:
    toks: List[Token] = []
    i, line, col = 0, 1, 1
    n = len(src)

    def err(msg):
        raise RegoParseError(msg, line, col)

    while i < n:
        c = src[i]
        if c == "\n":
            # collapse runs of newlines into one token
            if toks and toks[-1].kind not in ("newline",):
                toks.append(Token("newline", "\n", line, col))
            i += 1
            line += 1
            col = 1
            continue
        if c in " \t\r":
            i += 1
            col += 1
            continue
        if c == "#":
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c == "`":  # raw string
            j = src.find("`", i + 1)
            if j < 0:
                err("unterminated raw string")
            toks.append(Token("string", src[i + 1 : j], line, col))
            col += j + 1 - i
            line += src.count("\n", i, j + 1)
            i = j + 1
            continue
        if c == '"':
            j = i + 1
            buf = []
            while j < n and src[j] != '"':
                if src[j] == "\\":
                    if j + 1 >= n:
                        err("unterminated string escape")
                    esc = src[j + 1]
                    mapping = {
                        "n": "\n",
                        "t": "\t",
                        "r": "\r",
                        '"': '"',
                        "\\": "\\",
                        "/": "/",
                        "b": "\b",
                        "f": "\f",
                    }
                    if esc == "u":
                        if j + 6 > n:
                            err("bad unicode escape")
                        buf.append(chr(int(src[j + 2 : j + 6], 16)))
                        j += 6
                        continue
                    if esc not in mapping:
                        err(f"bad escape \\{esc}")
                    buf.append(mapping[esc])
                    j += 2
                    continue
                if src[j] == "\n":
                    err("newline in string")
                buf.append(src[j])
                j += 1
            if j >= n:
                err("unterminated string")
            toks.append(Token("string", "".join(buf), line, col))
            col += j + 1 - i
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            isfloat = False
            while j < n and (src[j].isdigit() or src[j] in ".eE+-"):
                if src[j] in "+-" and src[j - 1] not in "eE":
                    break
                if src[j] in ".eE":
                    isfloat = True
                j += 1
            text = src[i:j]
            try:
                val = float(text) if isfloat else int(text)
            except ValueError:
                err(f"bad number {text!r}")
            if isfloat and float(val).is_integer() and "e" not in text and "E" not in text:
                val = int(val)
            toks.append(Token("number", val, line, col))
            col += j - i
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            kind = "kw" if word in KEYWORDS else "ident"
            toks.append(Token(kind, word, line, col))
            col += j - i
            i = j
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(Token("punct", p, line, col))
                i += len(p)
                col += len(p)
                break
        else:
            err(f"unexpected character {c!r}")
    toks.append(Token("eof", None, line, col))
    return toks
