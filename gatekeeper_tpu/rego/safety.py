"""Body reordering for variable safety.

Rego bodies are declarative: statement order does not determine evaluation
order.  OPA's compiler reorders body expressions so every variable is bound
before use (ast/compile.go "reordering for safety"); e.g. the corpus's
k8suniqueserviceselector writes

    selectors := [s | s = concat(":", [key, val]); val = obj.spec.selector[key]]

where `key`/`val` are bound by the *second* statement.  This pass performs the
same greedy topological reorder over (needs, binds) var sets, recursively
including comprehension bodies.
"""

from __future__ import annotations

from typing import FrozenSet, List, Set, Tuple

from .ast import (
    ArrayCompr,
    ArrayTerm,
    BinOp,
    Body,
    Call,
    Expr,
    Module,
    Node,
    ObjectCompr,
    ObjectTerm,
    Ref,
    Rule,
    Scalar,
    SetCompr,
    SetTerm,
    UnaryMinus,
    Var,
)

_GLOBALS = frozenset({"input", "data"})


class _Analysis:
    __slots__ = ("needs", "binds")

    def __init__(self):
        self.needs: Set[str] = set()
        self.binds: Set[str] = set()


def _is_special(name: str, rule_names: FrozenSet[str]) -> bool:
    return name in _GLOBALS or name in rule_names


import threading as _threading

_REORDER_TLS = _threading.local()  # per-compile local-function arity map


def _is_output_form(t: Call) -> bool:
    """True when a statement-level call carries an extra output argument
    (declared arity + 1).  Builtin arities come from the engine registry
    (function-level import: engine.builtins depends only on engine.value,
    so no cycle with this package); module-local function arities come
    from the thread-local map reorder_module installs.  data.lib
    cross-module calls are unknown here and fall back to source order."""
    from ..engine.builtins import lookup

    fn = lookup(t.path)
    if fn is not None:
        return len(t.args) == fn._rego_arity + 1
    if len(t.path) == 1:
        arity = getattr(_REORDER_TLS, "arities", {}).get(t.path[0])
        if arity is not None:
            return len(t.args) == arity + 1
    return False


def _walk(t: Node, pos: str, a: _Analysis, rule_names: FrozenSet[str]):
    """pos: 'pattern' (vars get bound) or 'eval' (vars must be bound)."""
    if isinstance(t, Scalar):
        return
    if isinstance(t, Var):
        if t.is_wildcard or _is_special(t.name, rule_names):
            return
        (a.binds if pos == "pattern" else a.needs).add(t.name)
        return
    if isinstance(t, Ref):
        if isinstance(t.head, Var):
            if not t.head.is_wildcard and not _is_special(t.head.name, rule_names):
                a.needs.add(t.head.name)
        else:
            _walk(t.head, "eval", a, rule_names)
        for op in t.operands:
            if isinstance(op, Var):
                if not op.is_wildcard and not _is_special(op.name, rule_names):
                    a.binds.add(op.name)  # enumeration binds ref operands
            elif isinstance(op, (ArrayTerm, ObjectTerm)):
                _walk(op, "pattern", a, rule_names)
            else:
                _walk(op, "eval", a, rule_names)
        return
    if isinstance(t, Call):
        for arg in t.args:
            _walk(arg, "eval", a, rule_names)
        return
    if isinstance(t, BinOp):
        _walk(t.lhs, "eval", a, rule_names)
        _walk(t.rhs, "eval", a, rule_names)
        return
    if isinstance(t, UnaryMinus):
        _walk(t.operand, "eval", a, rule_names)
        return
    if isinstance(t, (ArrayTerm, SetTerm)):
        inner_pos = pos if isinstance(t, ArrayTerm) else "eval"
        for item in t.items:
            _walk(item, inner_pos, a, rule_names)
        return
    if isinstance(t, ObjectTerm):
        for k, v in t.pairs:
            _walk(k, "eval", a, rule_names)
            _walk(v, pos, a, rule_names)
        return
    if isinstance(t, (ArrayCompr, SetCompr, ObjectCompr)):
        # Comprehensions have local scope: they need only the free variables
        # their bodies cannot bind internally.
        sub = _Analysis()
        for e in t.body:
            en, eb = _expr_analysis(e, rule_names)
            sub.needs |= en
            sub.binds |= eb
        heads = (
            (t.key, t.value) if isinstance(t, ObjectCompr) else (t.head,)
        )
        for h in heads:
            _walk(h, "eval", sub, rule_names)
        a.needs |= sub.needs - sub.binds
        return
    raise TypeError(f"unexpected node {type(t).__name__}")


def _all_vars(t: Node, rule_names: FrozenSet[str], out: Set[str]):
    a = _Analysis()
    _walk(t, "eval", a, rule_names)
    out |= a.needs | a.binds


def _expr_analysis(e: Expr, rule_names: FrozenSet[str]) -> Tuple[Set[str], Set[str]]:
    a = _Analysis()
    if e.withs:
        # with-values must be bound before the modified literal runs
        wa = _Analysis()
        for _path, v in e.withs:
            _walk(v, "eval", wa, rule_names)
        base = Expr(e.kind, e.terms, e.loc)
        n, b = _expr_analysis(base, rule_names)
        return n | wa.needs, b
    if e.kind == "some":
        return set(), set()
    if e.kind == "not":
        # Negation safety: everything under `not` must already be bound.
        inner = e.terms[0]
        needs: Set[str] = set()
        for t in inner.terms:
            if isinstance(t, Expr):
                n2, b2 = _expr_analysis(t, rule_names)
                needs |= n2 | b2
            else:
                _all_vars(t, rule_names, needs)
        return needs, set()
    if e.kind in ("unify", "assign"):
        for side in e.terms:
            if isinstance(side, Var):
                if not side.is_wildcard and not _is_special(side.name, rule_names):
                    a.binds.add(side.name)
            elif isinstance(side, (ArrayTerm, ObjectTerm)):
                _walk(side, "pattern", a, rule_names)
            else:
                _walk(side, "eval", a, rule_names)
        return a.needs, a.binds
    t0 = e.terms[0]
    if isinstance(t0, Call) and _is_output_form(t0):
        # statement-level output-argument call: f(in..., out) binds out
        # (and walk(x, [p, v]) binds p/v — OPA's relational builtin)
        for arg in t0.args[:-1]:
            _walk(arg, "eval", a, rule_names)
        _walk(t0.args[-1], "pattern", a, rule_names)
        return a.needs, a.binds
    _walk(t0, "eval", a, rule_names)
    return a.needs, a.binds


def reorder_body(body: Body, initial_bound: Set[str], rule_names: FrozenSet[str]) -> Body:
    if len(body) <= 1:
        return body
    infos = [(e, *_expr_analysis(e, rule_names)) for e in body]
    binds_all: Set[str] = set()
    needs_all: Set[str] = set()
    for _e, n, b in infos:
        binds_all |= b
        needs_all |= n
    # Vars never bound in this body are assumed bound by the enclosing scope
    # (comprehension over outer vars) — or genuinely unsafe, surfacing at eval.
    bound = set(initial_bound) | (needs_all - binds_all)
    remaining = list(infos)
    ordered: List[Expr] = []
    while remaining:
        progress = False
        for i, (e, n, b) in enumerate(remaining):
            if n <= bound:
                ordered.append(e)
                bound |= b
                remaining.pop(i)
                progress = True
                break
        if not progress:
            # Cannot order safely (e.g. mutually-recursive negation);
            # keep original order for the tail — eval will surface errors.
            ordered.extend(e for e, _n, _b in remaining)
            break
    return tuple(ordered)


def _transform_term(t: Node, rule_names: FrozenSet[str]) -> Node:
    if isinstance(t, (Scalar, Var)):
        return t
    if isinstance(t, Ref):
        return Ref(
            _transform_term(t.head, rule_names),  # type: ignore[arg-type]
            tuple(_transform_term(op, rule_names) for op in t.operands),
        )
    if isinstance(t, Call):
        return Call(t.path, tuple(_transform_term(x, rule_names) for x in t.args))
    if isinstance(t, BinOp):
        return BinOp(t.op, _transform_term(t.lhs, rule_names), _transform_term(t.rhs, rule_names))
    if isinstance(t, UnaryMinus):
        return UnaryMinus(_transform_term(t.operand, rule_names))
    if isinstance(t, ArrayTerm):
        return ArrayTerm(tuple(_transform_term(x, rule_names) for x in t.items))
    if isinstance(t, SetTerm):
        return SetTerm(tuple(_transform_term(x, rule_names) for x in t.items))
    if isinstance(t, ObjectTerm):
        return ObjectTerm(
            tuple(
                (_transform_term(k, rule_names), _transform_term(v, rule_names))
                for k, v in t.pairs
            )
        )
    if isinstance(t, ArrayCompr):
        return ArrayCompr(
            _transform_term(t.head, rule_names),
            transform_body(t.body, set(), rule_names),
        )
    if isinstance(t, SetCompr):
        return SetCompr(
            _transform_term(t.head, rule_names),
            transform_body(t.body, set(), rule_names),
        )
    if isinstance(t, ObjectCompr):
        return ObjectCompr(
            _transform_term(t.key, rule_names),
            _transform_term(t.value, rule_names),
            transform_body(t.body, set(), rule_names),
        )
    raise TypeError(f"unexpected node {type(t).__name__}")


def _transform_expr(e: Expr, rule_names: FrozenSet[str]) -> Expr:
    withs = tuple(
        (p, _transform_term(v, rule_names)) for p, v in e.withs
    )
    if e.kind == "not":
        return Expr(
            "not", (_transform_expr(e.terms[0], rule_names),), e.loc, withs=withs
        )
    if e.kind == "some":
        return e
    return Expr(
        e.kind,
        tuple(_transform_term(t, rule_names) for t in e.terms),
        e.loc,
        withs=withs,
    )


def transform_body(body: Body, initial_bound: Set[str], rule_names: FrozenSet[str]) -> Body:
    transformed = tuple(_transform_expr(e, rule_names) for e in body)
    return reorder_body(transformed, initial_bound, rule_names)


def _reorder_rule(r: Rule, params: Set[str], rule_names: FrozenSet[str]) -> Rule:
    body = transform_body(r.body, params, rule_names)
    key = _transform_term(r.key, rule_names) if r.key is not None else None
    value = _transform_term(r.value, rule_names) if r.value is not None else None
    # else clauses share the head clause's parameter scope
    els = _reorder_rule(r.els, params, rule_names) if r.els is not None else None
    return Rule(r.name, r.args, key, value, body, r.is_default, r.loc, els=els)


def reorder_module(module: Module) -> Module:
    """Reorder every rule body (and nested comprehension bodies) for safety."""
    rule_names = frozenset(r.name for r in module.rules)
    _REORDER_TLS.arities = {
        r.name: len(r.args) for r in module.rules if r.args is not None
    }
    try:
        new_rules = []
        for r in module.rules:
            params: Set[str] = set()
            if r.args:
                a = _Analysis()
                for p in r.args:
                    _walk(p, "pattern", a, rule_names)
                params = a.binds
            new_rules.append(_reorder_rule(r, params, rule_names))
    finally:
        _REORDER_TLS.arities = {}
    return Module(package=module.package, rules=tuple(new_rules), source=module.source)
