from .ast import (  # noqa: F401
    Module,
    RegoCompileError,
    RegoError,
    RegoParseError,
    Rule,
)
from .parser import parse_module  # noqa: F401
