"""Recursive-descent parser for the Rego subset (see ast.py for scope)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from .ast import (
    ArrayCompr,
    ArrayTerm,
    BinOp,
    Body,
    Call,
    Expr,
    Module,
    Node,
    ObjectCompr,
    ObjectTerm,
    Ref,
    RegoParseError,
    Rule,
    Scalar,
    SetCompr,
    SetTerm,
    UnaryMinus,
    Var,
)
from .scanner import Token, scan

_REL_OPS = {"==", "!=", "<", "<=", ">", ">="}
_ADD_OPS = {"+", "-"}
_MUL_OPS = {"*", "/", "%"}


class Parser:
    def __init__(self, src: str):
        self.toks: List[Token] = scan(src)
        self.pos = 0
        self._wild = 0
        self.src = src
        self.imports: dict = {}  # alias -> full path, filled by parse_module

    # ---- token helpers ----------------------------------------------------

    def cur(self) -> Token:
        return self.toks[self.pos]

    def advance(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "eof":
            self.pos += 1
        return t

    def at(self, kind: str, value=None) -> bool:
        t = self.cur()
        return t.kind == kind and (value is None or t.value == value)

    def at_punct(self, *vals: str) -> bool:
        t = self.cur()
        return t.kind == "punct" and t.value in vals

    def expect(self, kind: str, value=None) -> Token:
        t = self.cur()
        if t.kind != kind or (value is not None and t.value != value):
            raise RegoParseError(
                f"expected {value or kind}, got {t.value!r}", t.line, t.col
            )
        return self.advance()

    def skip_nl(self):
        while self.at("newline"):
            self.advance()

    def err(self, msg: str):
        t = self.cur()
        raise RegoParseError(msg, t.line, t.col)

    def fresh_wild(self) -> Var:
        self._wild += 1
        return Var(f"$wild{self._wild}")

    # ---- module -----------------------------------------------------------

    def parse_module(self) -> Module:
        self.skip_nl()
        self.expect("kw", "package")
        pkg = self.parse_package_path()
        rules: List[Rule] = []
        self.skip_nl()
        # Imports bind an alias (`import data.lib.helpers` -> `helpers`,
        # `import data.lib.x as y` -> `y`) that OPA resolves at compile time
        # (vendored opa/ast resolves import aliases during rewriting); we do
        # the same with a post-parse AST rewrite so safety analysis, the
        # interpreter, and the vectorizer all see fully-qualified refs.
        imports: dict = {}
        while self.at("kw", "import"):
            tok = self.cur()
            self.advance()
            path = self.parse_package_path()
            alias: Optional[str] = None
            if self.at("kw", "as"):
                self.advance()
                alias = self.expect("ident").value
            if path[0] not in ("data", "input"):
                raise RegoParseError(
                    "import path must begin with data or input", tok.line, tok.col
                )
            name = alias or path[-1]
            if name in imports:
                raise RegoParseError(
                    f"import must not shadow import '{name}'", tok.line, tok.col
                )
            imports[name] = tuple(path)
            self.imports = imports  # visible to with-target resolution
            self.skip_nl()
        while not self.at("eof"):
            rules.append(self.parse_rule())
            self.skip_nl()
        if imports:
            _check_import_shadowing(rules, imports)
            rules = [_rewrite_rule_imports(r, imports) for r in rules]
        return Module(package=tuple(pkg), rules=tuple(rules), source=self.src)

    def parse_package_path(self) -> List[str]:
        parts = [self.expect("ident").value]
        while True:
            if self.at_punct("."):
                self.advance()
                parts.append(self.expect("ident").value)
            elif self.at_punct("["):
                self.advance()
                parts.append(self.expect("string").value)
                self.expect("punct", "]")
            else:
                break
        return parts

    # ---- rules ------------------------------------------------------------

    def parse_rule(self) -> Rule:
        loc = (self.cur().line, self.cur().col)
        is_default = False
        if self.at("kw", "default"):
            is_default = True
            self.advance()
        name = self.expect("ident").value
        args: Optional[Tuple[Node, ...]] = None
        key: Optional[Node] = None
        value: Optional[Node] = None
        if self.at_punct("("):
            self.advance()
            self.skip_nl()
            arglist = []
            while not self.at_punct(")"):
                arglist.append(self.parse_term())
                self.skip_nl()
                if self.at_punct(","):
                    self.advance()
                    self.skip_nl()
            self.advance()
            args = tuple(arglist)
        elif self.at_punct("["):
            self.advance()
            self.skip_nl()
            key = self.parse_term()
            self.skip_nl()
            self.expect("punct", "]")
        if self.at_punct("=", ":="):
            self.advance()
            self.skip_nl()
            value = self.parse_term()
        if is_default:
            if value is None:
                self.err("default rule requires a value")
            return Rule(name, None, None, value, (), is_default=True, loc=loc)
        body: Body = ()
        if self.at_punct("{"):
            body = self.parse_body()
        elif value is None:
            # Only `name = value` / `f(x) = value` constants may omit the body.
            self.err("rule requires a body or value")
        els = self._parse_else_chain(key)
        if key is not None and value is None and args is None:
            # partial set rule
            return Rule(name, None, key, None, body, loc=loc)
        return Rule(name, args, key, value, body, loc=loc, els=els)

    def _parse_else_chain(self, key: Optional[Node]) -> Optional[Rule]:
        """Parse `else [= value] { body }`... into a linked clause chain
        (OPA else semantics: clauses tried in order, first success wins)."""
        save = self.pos
        self.skip_nl()
        if not self.at("kw", "else"):
            self.pos = save
            return None
        if key is not None:
            self.err("'else' is not valid on partial rules")
        loc = (self.cur().line, self.cur().col)
        self.advance()
        value: Optional[Node] = None
        if self.at_punct("=", ":="):
            self.advance()
            self.skip_nl()
            value = self.parse_term()
        body: Body = ()
        if self.at_punct("{"):
            body = self.parse_body()
        elif value is None:
            # OPA grammar: rule-else ::= "else" [ "=" term ] [ "{" query "}" ]
            self.err("'else' requires a value or a body")
        els = self._parse_else_chain(key)
        return Rule("else", None, None, value, body, loc=loc, els=els)

    def parse_body(self) -> Body:
        self.expect("punct", "{")
        return self._parse_statements(closer="}")

    def _parse_statements(self, closer: str) -> Body:
        stmts: List[Expr] = []
        self.skip_nl()
        while not self.at_punct(closer):
            stmts.append(self.parse_statement())
            if self.at_punct(";"):
                self.advance()
                self.skip_nl()
            elif self.at("newline"):
                self.skip_nl()
            elif not self.at_punct(closer):
                self.err("expected end of statement")
        self.advance()  # consume closer
        return tuple(stmts)

    def parse_statement(self) -> Expr:
        t = self.cur()
        loc = (t.line, t.col)
        if self.at("kw", "some"):
            self.advance()
            names = [Var(self.expect("ident").value)]
            while self.at_punct(","):
                self.advance()
                names.append(Var(self.expect("ident").value))
            return Expr("some", tuple(names), loc)
        if self.at("kw", "not"):
            self.advance()
            inner = self.parse_statement_core(loc)
            e = Expr("not", (inner,), loc)
        else:
            e = self.parse_statement_core(loc)
        withs = self._parse_with_modifiers()
        if withs:
            # `with` scopes the whole literal, including its negation
            e = Expr(e.kind, e.terms, e.loc, withs=withs)
        return e

    def _parse_with_modifiers(self):
        """`<literal> with <target> as <value>`...  Targets: input[...] or
        data.inventory[...] (OPA v0.21 restricts `with` to input and base
        documents; the inventory is this engine's only base document)."""
        withs = []
        while self.at("kw", "with"):
            tok = self.cur()
            self.advance()
            path = tuple(self.parse_package_path())
            if path[0] in self.imports:
                # aliases resolve in with targets too (OPA resolves them
                # during compile-stage rewriting)
                path = self.imports[path[0]] + path[1:]
            if not (
                path[0] == "input"
                or (path[0] == "data" and path[1:2] == ("inventory",))
            ):
                raise RegoParseError(
                    "'with' targets must be input[...] or data.inventory[...]",
                    tok.line,
                    tok.col,
                )
            self.expect("kw", "as")
            self.skip_nl()
            value = self.parse_term()
            withs.append((path, value))
        return tuple(withs)

    def parse_statement_core(self, loc) -> Expr:
        lhs = self.parse_term()
        if self.at_punct("="):
            self.advance()
            self.skip_nl()
            rhs = self.parse_term()
            return Expr("unify", (lhs, rhs), loc)
        if self.at_punct(":="):
            self.advance()
            self.skip_nl()
            rhs = self.parse_term()
            return Expr("assign", (lhs, rhs), loc)
        return Expr("term", (lhs,), loc)

    # ---- terms (precedence climbing) --------------------------------------

    def parse_term(self) -> Node:
        return self.parse_or()

    def _binop_chain(self, sub, ops):
        lhs = sub()
        while self.cur().kind == "punct" and self.cur().value in ops:
            op = self.advance().value
            self.skip_nl()
            rhs = sub()
            lhs = BinOp(op, lhs, rhs)
        return lhs

    def parse_or(self) -> Node:
        return self._binop_chain(self.parse_and, {"|"})

    def parse_and(self) -> Node:
        return self._binop_chain(self.parse_rel, {"&"})

    def parse_rel(self) -> Node:
        lhs = self.parse_add()
        if self.cur().kind == "punct" and self.cur().value in _REL_OPS:
            op = self.advance().value
            self.skip_nl()
            rhs = self.parse_add()
            return BinOp(op, lhs, rhs)
        return lhs

    def parse_add(self) -> Node:
        return self._binop_chain(self.parse_mul, _ADD_OPS)

    def parse_mul(self) -> Node:
        return self._binop_chain(self.parse_unary, _MUL_OPS)

    def parse_unary(self) -> Node:
        if self.at_punct("-"):
            self.advance()
            operand = self.parse_unary()
            if isinstance(operand, Scalar) and isinstance(operand.value, (int, float)):
                return Scalar(-operand.value)
            return UnaryMinus(operand)
        return self.parse_postfix()

    def parse_postfix(self) -> Node:
        base = self.parse_primary()
        # Collect a dotted/bracketed ref chain; a '(' turns the chain so far
        # into a call (builtin dotted path or user function).
        while True:
            if self.at_punct("."):
                self.advance()
                fld = self.expect("ident").value
                base = self._extend_ref(base, Scalar(fld))
            elif self.at_punct("["):
                self.advance()
                self.skip_nl()
                idx = self.parse_term()
                self.skip_nl()
                self.expect("punct", "]")
                base = self._extend_ref(base, idx)
            elif self.at_punct("("):
                path = self._ref_to_path(base)
                if path is None:
                    self.err("cannot call a non-identifier term")
                self.advance()
                self.skip_nl()
                args = []
                while not self.at_punct(")"):
                    args.append(self.parse_term())
                    self.skip_nl()
                    if self.at_punct(","):
                        self.advance()
                        self.skip_nl()
                self.advance()
                if path == ("set",) and not args:
                    base = SetTerm(())
                else:
                    base = Call(tuple(path), tuple(args))
            else:
                break
        return base

    def _extend_ref(self, base: Node, operand: Node) -> Node:
        if isinstance(base, Ref):
            return Ref(base.head, base.operands + (operand,))
        if isinstance(base, Var):
            return Ref(base, (operand,))
        if isinstance(base, (Call, ArrayTerm, ObjectTerm, SetTerm)):
            # indexing a call result / literal: model as ref with synthetic head
            return Ref(base, (operand,))  # type: ignore[arg-type]
        self.err("cannot index this term")

    @staticmethod
    def _ref_to_path(base: Node) -> Optional[Tuple[str, ...]]:
        if isinstance(base, Var):
            return (base.name,)
        if isinstance(base, Ref) and isinstance(base.head, Var):
            parts = [base.head.name]
            for op in base.operands:
                if isinstance(op, Scalar) and isinstance(op.value, str):
                    parts.append(op.value)
                else:
                    return None
            return tuple(parts)
        return None

    def parse_primary(self) -> Node:
        t = self.cur()
        if t.kind == "number":
            self.advance()
            return Scalar(t.value)
        if t.kind == "string":
            self.advance()
            return Scalar(t.value)
        if t.kind == "kw" and t.value in ("true", "false", "null"):
            self.advance()
            return Scalar({"true": True, "false": False, "null": None}[t.value])
        if t.kind == "ident":
            self.advance()
            if t.value == "_":
                return self.fresh_wild()
            return Var(t.value)
        if self.at_punct("("):
            self.advance()
            self.skip_nl()
            inner = self.parse_term()
            self.skip_nl()
            self.expect("punct", ")")
            return inner
        if self.at_punct("["):
            return self.parse_array()
        if self.at_punct("{"):
            return self.parse_brace()
        self.err(f"unexpected token {t.value!r}")

    def parse_array(self) -> Node:
        self.expect("punct", "[")
        self.skip_nl()
        if self.at_punct("]"):
            self.advance()
            return ArrayTerm(())
        # Parse below '|' precedence: '|' here separates a comprehension head
        # from its body, not a set union.
        first = self.parse_and()
        self.skip_nl()
        if self.at_punct("|"):
            self.advance()
            body = self._parse_statements(closer="]")
            return ArrayCompr(first, body)
        items = [first]
        while self.at_punct(","):
            self.advance()
            self.skip_nl()
            if self.at_punct("]"):
                break
            items.append(self.parse_term())
            self.skip_nl()
        self.expect("punct", "]")
        return ArrayTerm(tuple(items))

    def parse_brace(self) -> Node:
        self.expect("punct", "{")
        self.skip_nl()
        if self.at_punct("}"):
            self.advance()
            return ObjectTerm(())
        # Parse below '|' precedence: '|' here separates a comprehension head
        # from its body, not a set union.
        first = self.parse_and()
        self.skip_nl()
        if self.at_punct(":"):
            self.advance()
            self.skip_nl()
            val = self.parse_and()
            self.skip_nl()
            if self.at_punct("|"):
                self.advance()
                body = self._parse_statements(closer="}")
                return ObjectCompr(first, val, body)
            pairs = [(first, val)]
            while self.at_punct(","):
                self.advance()
                self.skip_nl()
                if self.at_punct("}"):
                    break
                k = self.parse_term()
                self.skip_nl()
                self.expect("punct", ":")
                self.skip_nl()
                v = self.parse_term()
                pairs.append((k, v))
                self.skip_nl()
            self.expect("punct", "}")
            return ObjectTerm(tuple(pairs))
        if self.at_punct("|"):
            self.advance()
            body = self._parse_statements(closer="}")
            return SetCompr(first, body)
        items = [first]
        while self.at_punct(","):
            self.advance()
            self.skip_nl()
            if self.at_punct("}"):
                break
            items.append(self.parse_term())
            self.skip_nl()
        self.expect("punct", "}")
        return SetTerm(tuple(items))


def _alias_ref(path) -> Ref:
    return Ref(Var(path[0]), tuple(Scalar(p) for p in path[1:]))


def _pattern_vars(node: Node, out: set):
    """Vars bound by an assignment-LHS / parameter pattern."""
    if isinstance(node, Var):
        if not node.is_wildcard:
            out.add(node.name)
    elif isinstance(node, ArrayTerm):
        for i in node.items:
            _pattern_vars(i, out)
    elif isinstance(node, ObjectTerm):
        for _k, v in node.pairs:
            _pattern_vars(v, out)


def _check_import_shadowing(rules, imp: dict):
    """OPA rejects local declarations that shadow an import alias
    ('variables must not shadow import'); without this check the rewrite
    below would silently mis-evaluate such programs instead of erroring."""

    def check_body(body: Body, loc):
        for e in body:
            bound: set = set()
            if e.kind == "some":
                for v in e.terms:
                    if isinstance(v, Var):
                        bound.add(v.name)
            elif e.kind == "assign":
                _pattern_vars(e.terms[0], bound)
            clash = bound & imp.keys()
            if clash:
                raise RegoParseError(
                    f"variables must not shadow import '{sorted(clash)[0]}'",
                    *e.loc,
                )
            for t in e.terms:
                check_term(t, e.loc)
            for _p, v in e.withs:
                check_term(v, e.loc)

    def check_term(t: Node, loc):
        if isinstance(t, (ArrayCompr, SetCompr)):
            check_body(t.body, loc)
        elif isinstance(t, ObjectCompr):
            check_body(t.body, loc)
        elif isinstance(t, Expr):
            check_body((t,), loc)
        elif isinstance(t, Ref):
            for op in t.operands:
                check_term(op, loc)
        elif isinstance(t, Call):
            for a in t.args:
                check_term(a, loc)
        elif isinstance(t, BinOp):
            check_term(t.lhs, loc)
            check_term(t.rhs, loc)
        elif isinstance(t, (ArrayTerm, SetTerm)):
            for i in t.items:
                check_term(i, loc)
        elif isinstance(t, ObjectTerm):
            for k, v in t.pairs:
                check_term(k, loc)
                check_term(v, loc)

    for rule in rules:
        clause = rule
        while clause is not None:
            if clause.name in imp:
                raise RegoParseError(
                    f"rule must not shadow import '{clause.name}'", *clause.loc
                )
            if clause.args:
                bound: set = set()
                for p in clause.args:
                    _pattern_vars(p, bound)
                clash = bound & imp.keys()
                if clash:
                    raise RegoParseError(
                        f"variables must not shadow import '{sorted(clash)[0]}'",
                        *clause.loc,
                    )
            check_body(clause.body, clause.loc)
            for t in (clause.key, clause.value):
                if t is not None:
                    check_term(t, clause.loc)
            clause = clause.els


def _rewrite_rule_imports(rule: Rule, imp: dict) -> Rule:
    """Replace import-alias references with their fully-qualified paths.

    OPA rejects local bindings that shadow an import alias, so unconditional
    substitution matches its semantics for all accepted programs.
    """

    def rw(node: Node) -> Node:
        if isinstance(node, Var):
            p = imp.get(node.name)
            return _alias_ref(p) if p else node
        if isinstance(node, Ref):
            ops = tuple(rw(o) for o in node.operands)
            head = node.head
            if isinstance(head, Var):
                p = imp.get(head.name)
                if p:
                    return Ref(Var(p[0]), tuple(Scalar(s) for s in p[1:]) + ops)
                return Ref(head, ops)
            return Ref(rw(head), ops)  # type: ignore[arg-type]
        if isinstance(node, Call):
            path = node.path
            p = imp.get(path[0])
            if p:
                path = p + path[1:]
            return Call(path, tuple(rw(a) for a in node.args))
        if isinstance(node, ArrayTerm):
            return ArrayTerm(tuple(rw(i) for i in node.items))
        if isinstance(node, SetTerm):
            return SetTerm(tuple(rw(i) for i in node.items))
        if isinstance(node, ObjectTerm):
            return ObjectTerm(tuple((rw(k), rw(v)) for k, v in node.pairs))
        if isinstance(node, ArrayCompr):
            return ArrayCompr(rw(node.head), rw_body(node.body))
        if isinstance(node, SetCompr):
            return SetCompr(rw(node.head), rw_body(node.body))
        if isinstance(node, ObjectCompr):
            return ObjectCompr(rw(node.key), rw(node.value), rw_body(node.body))
        if isinstance(node, BinOp):
            return BinOp(node.op, rw(node.lhs), rw(node.rhs))
        if isinstance(node, UnaryMinus):
            return UnaryMinus(rw(node.operand))
        return node

    def rw_expr(e: Expr) -> Expr:
        withs = tuple((p, rw(v)) for p, v in e.withs)
        if e.kind == "some":  # declarations, not references
            return e
        if e.kind == "not":
            return Expr("not", (rw_expr(e.terms[0]),), e.loc, withs=withs)  # type: ignore[arg-type]
        return Expr(e.kind, tuple(rw(t) for t in e.terms), e.loc, withs=withs)

    def rw_body(body: Body) -> Body:
        return tuple(rw_expr(e) for e in body)

    return Rule(
        name=rule.name,
        args=tuple(rw(a) for a in rule.args) if rule.args is not None else None,
        key=rw(rule.key) if rule.key is not None else None,
        value=rw(rule.value) if rule.value is not None else None,
        body=rw_body(rule.body),
        is_default=rule.is_default,
        loc=rule.loc,
        els=_rewrite_rule_imports(rule.els, imp) if rule.els is not None else None,
    )


def parse_module(src: str) -> Module:
    """Parse Rego source into a Module."""
    return Parser(src).parse_module()
