{{- define "gatekeeper-tpu.labels" -}}
app: gatekeeper-tpu
chart: {{ .Chart.Name }}
release: {{ .Release.Name }}
heritage: {{ .Release.Service }}
{{- end }}
